"""Multi-vector (ColBERT-style) index: MUVERA FDE + exact MaxSim rescore.

Reference: ``adapters/repos/db/vector/multivector/muvera.go:26`` (fixed
dimensional encoding) + ``hnsw/search.go:927`` (late-interaction rescore).
The reference encodes per-vector in scalar Go loops; here every stage is a
batched device op:

- SimHash bucket assignment: ONE [T, ksim] matmul per repetition (sign bits
  -> bucket id), vmapped over repetitions.
- Bucket aggregation: ``segment_sum`` over the token axis.
- Empty-bucket fill (docs only, as in MUVERA): hamming-nearest token via a
  popcount table over the [B, T] xor grid.
- Per-repetition ±1 projection: one [B, D] x [D, dproj] matmul.

The FDE corpus lives in a normal ``FlatIndex`` (dot metric, HBM-resident),
so the candidate search is the same masked-matmul + two-stage top-k kernel
as everything else; the final exact MaxSim (Chamfer) rescore over the top
candidates is a single padded ``[C, Tq, Td]`` einsum.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from weaviate_tpu.index.base import SearchResult, VectorIndex
from weaviate_tpu.index.flat import FlatIndex
from weaviate_tpu.schema.config import FlatIndexConfig, MultiVectorIndexConfig

MUVERA_SEED = 0x532C_A510


class MuveraEncoder:
    """Fixed-dimensional encoding of a token-vector set (MUVERA).

    fde_dim = repetitions * 2^ksim * dproj. Doc and query encodings differ
    exactly as in the paper: docs average + empty-fill, queries sum only.
    """

    def __init__(self, dims: int, ksim: int = 4, dproj: int = 16,
                 repetitions: int = 10):
        import jax

        self.dims = dims
        self.ksim = ksim
        self.dproj = min(dproj, dims)
        self.repetitions = repetitions
        self.buckets = 1 << ksim
        key = jax.random.PRNGKey(MUVERA_SEED)
        kg, kp = jax.random.split(key)
        # host copies: encoding happens in jitted fns that close over these
        # graftlint: allow[host-sync-in-hot-path] reason=one-shot init; jitted encoders close over host copies
        self.gaussians = np.asarray(
            jax.random.normal(kg, (repetitions, ksim, dims)), np.float32)
        # graftlint: allow[host-sync-in-hot-path] reason=one-shot init; jitted encoders close over host copies
        self.proj = np.asarray(
            jax.random.rademacher(kp, (repetitions, dims, self.dproj)),
            np.float32) / np.sqrt(self.dproj)
        self.fde_dim = repetitions * self.buckets * self.dproj
        self._bit_weights = (1 << np.arange(ksim)).astype(np.int32)

    # -- host-side (numpy): exact, no padding needed ------------------------
    def _bucket_ids(self, tokens: np.ndarray) -> np.ndarray:
        """[R, T] bucket ids from sign bits of the gaussian projections."""
        # [R, ksim, D] x [T, D] -> [R, ksim, T]
        dots = np.einsum("rkd,td->rkt", self.gaussians, tokens)
        bits = (dots < 0).astype(np.int32)
        return np.einsum("rkt,k->rt", bits, self._bit_weights)

    def encode_doc(self, tokens: np.ndarray) -> np.ndarray:
        """[T, D] -> [fde_dim]. Per bucket: MEAN of assigned tokens; empty
        buckets take the hamming-nearest token (MUVERA fill)."""
        tokens = np.asarray(tokens, np.float32)
        ids = self._bucket_ids(tokens)  # [R, T]
        out = np.zeros((self.repetitions, self.buckets, self.dims), np.float32)
        for r in range(self.repetitions):
            counts = np.bincount(ids[r], minlength=self.buckets).astype(np.float32)
            np.add.at(out[r], ids[r], tokens)
            nz = counts > 0
            out[r][nz] /= counts[nz][:, None]
            if not nz.all():
                # hamming distance between bucket index bits and token bits
                empty = np.nonzero(~nz)[0]
                xor = empty[:, None] ^ ids[r][None, :]  # [E, T]
                ham = np.vectorize(lambda x: bin(x).count("1"))(xor)
                nearest = np.argmin(ham, axis=1)
                out[r][empty] = tokens[nearest]
        # per-repetition projection: [B, D] @ [D, dp]
        proj = np.einsum("rbd,rdp->rbp", out, self.proj)
        return proj.reshape(-1)

    def encode_query(self, tokens: np.ndarray) -> np.ndarray:
        """[Tq, D] -> [fde_dim]. SUM per bucket, no fill (paper asymmetry)."""
        tokens = np.asarray(tokens, np.float32)
        ids = self._bucket_ids(tokens)
        out = np.zeros((self.repetitions, self.buckets, self.dims), np.float32)
        for r in range(self.repetitions):
            np.add.at(out[r], ids[r], tokens)
        proj = np.einsum("rbd,rdp->rbp", out, self.proj)
        return proj.reshape(-1)


def maxsim_scores(query: np.ndarray, cand_tokens: np.ndarray,
                  cand_mask: np.ndarray) -> np.ndarray:
    """Exact late-interaction (Chamfer/MaxSim) on device.

    query [Tq, D]; cand_tokens [C, Tmax, D] zero-padded; cand_mask [C, Tmax].
    Returns [C] scores = sum over query tokens of max over doc tokens of the
    dot product (reference hnsw/search.go:927 rescore loop -> one einsum).
    With an active device mesh the candidate axis shards across it
    (``parallel.sharded_maxsim``) — the rescore tier's sequence-parallel
    analogue for long token sets.
    """
    import jax.numpy as jnp

    from weaviate_tpu.parallel.runtime import default_mesh

    mesh = default_mesh()
    if mesh is not None and cand_tokens.shape[0] >= 2 * mesh.size:
        from weaviate_tpu.parallel.sharded_search import sharded_maxsim
        from jax.sharding import NamedSharding, PartitionSpec as P
        from weaviate_tpu.parallel.mesh import SHARD_AXIS

        c = cand_tokens.shape[0]
        pad = (-c) % mesh.size
        if pad:
            cand_tokens = np.concatenate(
                [cand_tokens, np.zeros((pad, *cand_tokens.shape[1:]),
                                       np.float32)])
            cand_mask = np.concatenate(
                [cand_mask, np.zeros((pad, cand_mask.shape[1]), bool)])
        import jax

        toks = jax.device_put(
            cand_tokens.astype(np.float32),
            NamedSharding(mesh, P(SHARD_AXIS, None, None)))
        mask = jax.device_put(cand_mask,
                              NamedSharding(mesh, P(SHARD_AXIS, None)))
        # replication of the query rides sharded_maxsim's identity-keyed
        # cache (one upload per query batch, not per invocation)
        q = np.asarray(query, np.float32)
        # graftlint: allow[host-sync-in-hot-path] reason=final [C] score materialization for host rerank
        return np.asarray(sharded_maxsim(q, toks, mask, mesh=mesh))[:c]

    q = jnp.asarray(query, jnp.float32)
    c = jnp.asarray(cand_tokens, jnp.float32)
    m = jnp.asarray(cand_mask, bool)
    sims = jnp.einsum("qd,ctd->cqt", q, c, preferred_element_type=jnp.float32)
    sims = jnp.where(m[:, None, :], sims, -jnp.inf)
    best = jnp.max(sims, axis=2)  # [C, Tq]
    best = jnp.where(jnp.isfinite(best), best, 0.0)
    # graftlint: allow[host-sync-in-hot-path] reason=final [C] score materialization for host rerank
    return np.asarray(jnp.sum(best, axis=1))


class MultiVectorIndex(VectorIndex):
    """FDE candidate index + token store + exact MaxSim rescore tier."""

    def __init__(self, dims: int, config: Optional[MultiVectorIndexConfig] = None):
        self.config = config or MultiVectorIndexConfig()
        self.dims = dims
        self.metric = "dot"  # FDE similarity is inner product
        self.encoder = MuveraEncoder(
            dims, ksim=self.config.ksim, dproj=self.config.dproj,
            repetitions=self.config.repetitions)
        inner_cfg = FlatIndexConfig(
            distance="dot",
            initial_capacity=self.config.initial_capacity,
            precision=self.config.precision,
            flat_approx_recall=self.config.flat_approx_recall,
        )
        self.inner = FlatIndex(self.encoder.fde_dim, inner_cfg)
        # device rerank tier (modules/device/): the exact MaxSim rescore
        # IS a rerank module here, fused with the FDE candidate scan into
        # ONE dispatch (ops/device_beam.fused_flat_rerank) — candidates
        # never round-trip to the host. config.rerank swaps the module.
        # The token store's host planes are the ONE host copy of the
        # token sets (rescore fallback + checkpoint both read them).
        from weaviate_tpu.modules.device import (
            CandidateTokenStore,
            build_device_reranker,
        )

        rr_cfg = getattr(self.config, "rerank", None)
        # explicit config vs the built-in default matters for the
        # fallback COUNTER only: an operator alerting on rerank
        # fallbacks must not see every unconfigured multivector
        # collection's normal host rescore firing the alert
        self._rerank_explicit = rr_cfg is not None and rr_cfg.enabled
        if self._rerank_explicit:
            self._rerank_module = build_device_reranker(
                rr_cfg.module, rr_cfg.params)
            tmax = rr_cfg.max_tokens
        else:
            self._rerank_module = build_device_reranker("rerank-maxsim")
            tmax = 8
        self._token_store = CandidateTokenStore(
            dims, max_tokens=tmax,
            cap_fn=lambda: self.inner.store.capacity,
            mesh=self.inner.store.mesh)

    multi_vector = True

    # -- writes -------------------------------------------------------------
    def add_batch_multi(self, doc_ids: np.ndarray,
                        token_sets: list[np.ndarray]) -> None:
        if len(doc_ids) == 0:
            return
        token_sets = [np.atleast_2d(np.asarray(t, np.float32))
                      for t in token_sets]
        # tokens BEFORE the candidate index: a racing search that sees the
        # new id in the FDE corpus must find its rescore tokens
        self._token_store.put(np.asarray(doc_ids, np.int64), token_sets)
        fdes = np.stack([self.encoder.encode_doc(t) for t in token_sets])
        self.inner.add_batch(np.asarray(doc_ids, np.int64), fdes)

    def _host_token_set(self, doc_id: int) -> Optional[np.ndarray]:
        """The exact (unpadded) token set for one doc from the host
        planes, or None when absent/deleted (mask rows are prefix-True,
        so the mask slice reconstructs the original shape)."""
        toks, mask = self._token_store.host_planes()
        if doc_id >= toks.shape[0]:
            return None
        m = mask[doc_id]
        if not m.any():
            return None
        return toks[doc_id][m]

    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        """Single-vector adds are degenerate token sets of size 1."""
        self.add_batch_multi(doc_ids, [v[None, :] if v.ndim == 1 else v
                                       for v in vectors])

    def delete(self, doc_ids: np.ndarray) -> None:
        self.inner.delete(doc_ids)
        self._token_store.delete(np.asarray(doc_ids).reshape(-1))

    # -- search ---------------------------------------------------------------
    def search_multi(self, query_tokens: np.ndarray, k: int,
                     allow_list: Optional[np.ndarray] = None) -> SearchResult:
        """query_tokens [Tq, D] -> top-k by the rerank module (exact
        MaxSim by default) over the FDE candidates (rescore_limit-wide).
        Device-resident single-chip stores run FDE scan + module score +
        top-k as ONE fused dispatch — candidates never visit the host;
        the legacy host rescore remains the (loud) fallback tier."""
        query_tokens = np.atleast_2d(np.asarray(query_tokens, np.float32))
        if query_tokens.shape[-1] != self.dims:
            raise ValueError(
                f"query token dims {query_tokens.shape[-1]} != {self.dims}")
        fde = self.encoder.encode_query(query_tokens)[None, :]
        cand_k = max(k, self.config.rescore_limit or 4 * k)
        cand_k = min(cand_k, max(1, self.inner.count()))
        if self.inner.store.device_resident and self.inner.store.mesh is None:
            res = self._search_multi_fused(query_tokens, fde, cand_k, k,
                                           allow_list)
            if res is not None:
                return res
        elif self._rerank_explicit:
            from weaviate_tpu.monitoring.metrics import RERANK_FALLBACK

            RERANK_FALLBACK.inc(
                module=self._rerank_module.name,
                reason="mesh_legacy" if self.inner.store.mesh is not None
                else "warm_tier")
        res = self.inner.search(fde, cand_k, allow_list)
        cand = res.ids[0]
        cand = cand[cand >= 0]
        if len(cand) == 0:
            return SearchResult(ids=np.full((1, k), -1, np.int64),
                                dists=np.full((1, k), np.inf, np.float32))
        # a candidate may have been deleted between the FDE search and here
        sets = []
        kept = []
        for d in cand:
            t = self._host_token_set(int(d))
            if t is not None:
                sets.append(t)
                kept.append(int(d))
        cand = np.asarray(kept, np.int64)
        if len(cand) == 0:
            return SearchResult(ids=np.full((1, k), -1, np.int64),
                                dists=np.full((1, k), np.inf, np.float32))
        tmax = max(s.shape[0] for s in sets)
        toks = np.zeros((len(sets), tmax, self.dims), np.float32)
        mask = np.zeros((len(sets), tmax), bool)
        for i, s in enumerate(sets):
            toks[i, : s.shape[0]] = s
            mask[i, : s.shape[0]] = True
        if self._rerank_module.name == "rerank-maxsim":
            # the default module IS this scorer — keep the (possibly
            # mesh-sharded, device-accelerated) implementation
            scores = maxsim_scores(query_tokens, toks, mask)
        else:
            # a configured non-default module must rank the fallback
            # tier too, or demotion would silently change the ordering
            # (docs/modules.md: the fallback runs the host_score twin)
            qm = np.ones((1, query_tokens.shape[0]), bool)
            scores = self._rerank_module.host_score(
                query_tokens[None], qm, toks[None], mask[None])[0]
        order = np.argsort(-scores, kind="stable")[:k]
        ids = np.full((1, k), -1, np.int64)
        d = np.full((1, k), np.inf, np.float32)
        ids[0, : len(order)] = cand[order]
        # present as a distance: negated MaxSim (lower = better)
        d[0, : len(order)] = -scores[order]
        return SearchResult(ids=ids, dists=d)

    def _search_multi_fused(self, query_tokens: np.ndarray,
                            fde: np.ndarray, cand_k: int, k: int,
                            allow_list: Optional[np.ndarray]
                            ) -> Optional[SearchResult]:
        """ONE dispatch: FDE scan → gather candidate token planes →
        module score → on-device top-k (``ops/device_beam.
        fused_flat_rerank``). Returns None to use the host path (the
        caller latches the fallback counter)."""
        import jax.numpy as jnp

        from weaviate_tpu.monitoring import tracing
        from weaviate_tpu.monitoring.metrics import (
            RERANK_CANDIDATES,
            RERANK_FALLBACK,
            RERANK_REQUESTS,
        )
        from weaviate_tpu.ops.device_beam import fused_flat_rerank

        name = self._rerank_module.name
        corpus, valid, _sqnorms = self.inner.store.snapshot()
        cap = int(corpus.shape[0])
        toks, tmask = self._token_store.sync(min_rows=cap)
        tq = query_tokens.shape[0]
        tq_pad = 1 << max(0, (tq - 1).bit_length())
        qt = np.zeros((1, tq_pad, self.dims), np.float32)
        qt[0, :tq] = query_tokens
        qm = np.zeros((1, tq_pad), bool)
        qm[0, :tq] = True
        allow_j = None
        if allow_list is not None:
            al = np.asarray(allow_list, bool)
            if len(al) < cap:
                al = np.pad(al, (0, cap - len(al)))
            allow_j = jnp.asarray(al[:cap])
        # pow2 buckets so steady traffic shares a handful of compiles
        fetch = 1 << max(3, (int(cand_k) - 1).bit_length())
        out_k = min(1 << max(3, (int(k) - 1).bit_length()), fetch)
        try:
            ids_j, d_j = fused_flat_rerank(
                self._rerank_module, jnp.asarray(fde), corpus, valid,
                jnp.asarray(qt), jnp.asarray(qm), toks, tmask,
                fetch=fetch, k=out_k, allow=allow_j, metric="dot",
                precision=self.config.precision)
            # graftlint: allow[host-sync-in-hot-path] reason=final reranked top-k materialization
            ids = np.asarray(ids_j)[0].astype(np.int64)
            # graftlint: allow[host-sync-in-hot-path] reason=final reranked top-k materialization
            d = np.asarray(d_j)[0].astype(np.float32)
        except Exception as e:
            import logging

            RERANK_FALLBACK.inc(module=name, reason="fused_error")
            logging.getLogger("weaviate_tpu.multivector").warning(
                "fused multivector rerank failed (host path serves this "
                "query): %s", e)
            return None
        RERANK_REQUESTS.inc(module=name, tier="fused")
        RERANK_CANDIDATES.observe(float(fetch), module=name)
        tracing.add_event("rerank.score", module=name,
                          candidates=int(fetch), rows=1)
        out_ids = np.full((1, k), -1, np.int64)
        out_d = np.full((1, k), np.inf, np.float32)
        n_out = min(k, len(ids))
        out_ids[0, :n_out] = ids[:n_out]
        out_d[0, :n_out] = d[:n_out]
        out_ids[0][~np.isfinite(out_d[0])] = -1
        return SearchResult(ids=out_ids, dists=out_d)

    def search(self, queries: np.ndarray, k: int,
               allow_list: Optional[np.ndarray] = None,
               est_selectivity: Optional[float] = None) -> SearchResult:
        """[B, D] single-vector queries (each = a 1-token set) or a single
        [Tq, D] token matrix via search_multi. ``est_selectivity`` is
        accepted for interface parity (planes resolve to host masks here)."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        outs = [self.search_multi(q[None, :], k, allow_list) for q in queries]
        return SearchResult(
            ids=np.concatenate([o.ids for o in outs]),
            dists=np.concatenate([o.dists for o in outs]),
        )

    def search_by_distance(self, queries, max_distance, allow_list=None,
                           limit: int = 1024):
        res = self.search(queries, min(limit, max(1, self.count())), allow_list)
        keep = res.dists <= max_distance
        return SearchResult(ids=np.where(keep, res.ids, -1),
                            dists=np.where(keep, res.dists, np.inf))

    # -- checkpoint ----------------------------------------------------------
    def save_vectors(self, path: str, meta: Optional[dict] = None) -> bool:
        """FDE corpus via the inner store + one token file (written from
        the token-store host planes — the one host copy) — boot becomes
        O(bytes) instead of an O(corpus) re-encode through the FDE loop."""
        import os

        import msgpack

        self.inner.store.save(path, meta)
        toks, mask = self._token_store.host_planes()
        live = np.flatnonzero(mask.any(axis=1))
        tmp = path + ".tokens.tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb({
                "version": 1,
                "docs": [
                    {"d": int(d),
                     "shape": [int(mask[d].sum()), self.dims],
                     "data": toks[d][mask[d]].tobytes()}
                    for d in live
                ],
            }, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path + ".tokens")
        return True

    def load_vectors(self, path: str) -> Optional[dict]:
        import os

        import msgpack

        meta = self.inner.store.load(path)
        if meta is None:
            return None
        tok_path = path + ".tokens"
        if not os.path.exists(tok_path):
            return None  # half a checkpoint is no checkpoint
        try:
            with open(tok_path, "rb") as f:
                d = msgpack.unpackb(f.read(), raw=False)
            if d.get("version") != 1:
                return None
            ids = [rec["d"] for rec in d["docs"]]
            sets = [
                np.frombuffer(rec["data"], np.float32)
                .reshape(rec["shape"]).copy()
                for rec in d["docs"]
            ]
        except (OSError, ValueError, KeyError, TypeError, AttributeError):
            # torn/corrupt token sidecar: contract is "rebuild from source"
            return None
        if ids:
            # a recovered index must rerank against the SAME token sets
            # it checkpointed, not empty masks
            self._token_store.put(np.asarray(ids, np.int64), sets)
        return meta

    # -- bookkeeping ---------------------------------------------------------
    def count(self) -> int:
        return self.inner.count()

    @property
    def capacity(self) -> int:
        return self.inner.capacity

    def contains(self, doc_id: int) -> bool:
        return self.inner.contains(doc_id)

    # -- tiered residency (docs/tiering.md): the FDE corpus is the inner
    # FlatIndex, whose warm tier serves demoted searches exactly; the
    # token store for the rescore tier is host-side already. Pure
    # delegation keeps the budget ledger seeing the real HBM rent.
    @property
    def device_resident(self) -> bool:
        return self.inner.device_resident

    def hbm_bytes(self) -> int:
        return self.inner.hbm_bytes() + self._token_store.nbytes

    def host_tier_bytes(self) -> int:
        return self.inner.host_tier_bytes() + self._token_store.host_bytes

    def demote_device(self) -> int:
        # the fused rerank's token planes are HBM rent exactly like the
        # FDE corpus — demotion drops both (host copies stay exact)
        return self.inner.demote_device() + self._token_store.drop_device()

    def promote_device(self) -> int:
        gained = self.inner.promote_device()
        if gained and self.inner.store.mesh is None:
            # the fused scan+rerank path is single-chip only; mesh mode
            # serves the rescore tier from host planes — uploading the
            # token planes there would be pure HBM rent for arrays no
            # program reads
            toks, tmask = self._token_store.sync()
            gained += sum(a.nbytes for a in (toks, tmask))
        return gained

    def stats(self) -> dict:
        return {
            "type": "multivector",
            "count": self.count(),
            "fde_dim": self.encoder.fde_dim,
            "token_dims": self.dims,
            "rerank_module": self._rerank_module.name,
            "rerank_hbm_bytes": self._token_store.nbytes,
        }
