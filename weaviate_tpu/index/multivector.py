"""Multi-vector (ColBERT-style) index: MUVERA FDE + exact MaxSim rescore.

Reference: ``adapters/repos/db/vector/multivector/muvera.go:26`` (fixed
dimensional encoding) + ``hnsw/search.go:927`` (late-interaction rescore).
The reference encodes per-vector in scalar Go loops; here every stage is a
batched device op:

- SimHash bucket assignment: ONE [T, ksim] matmul per repetition (sign bits
  -> bucket id), vmapped over repetitions.
- Bucket aggregation: ``segment_sum`` over the token axis.
- Empty-bucket fill (docs only, as in MUVERA): hamming-nearest token via a
  popcount table over the [B, T] xor grid.
- Per-repetition ±1 projection: one [B, D] x [D, dproj] matmul.

The FDE corpus lives in a normal ``FlatIndex`` (dot metric, HBM-resident),
so the candidate search is the same masked-matmul + two-stage top-k kernel
as everything else; the final exact MaxSim (Chamfer) rescore over the top
candidates is a single padded ``[C, Tq, Td]`` einsum.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from weaviate_tpu.index.base import SearchResult, VectorIndex
from weaviate_tpu.index.flat import FlatIndex
from weaviate_tpu.schema.config import FlatIndexConfig, MultiVectorIndexConfig

MUVERA_SEED = 0x532C_A510


class MuveraEncoder:
    """Fixed-dimensional encoding of a token-vector set (MUVERA).

    fde_dim = repetitions * 2^ksim * dproj. Doc and query encodings differ
    exactly as in the paper: docs average + empty-fill, queries sum only.
    """

    def __init__(self, dims: int, ksim: int = 4, dproj: int = 16,
                 repetitions: int = 10):
        import jax

        self.dims = dims
        self.ksim = ksim
        self.dproj = min(dproj, dims)
        self.repetitions = repetitions
        self.buckets = 1 << ksim
        key = jax.random.PRNGKey(MUVERA_SEED)
        kg, kp = jax.random.split(key)
        # host copies: encoding happens in jitted fns that close over these
        # graftlint: allow[host-sync-in-hot-path] reason=one-shot init; jitted encoders close over host copies
        self.gaussians = np.asarray(
            jax.random.normal(kg, (repetitions, ksim, dims)), np.float32)
        # graftlint: allow[host-sync-in-hot-path] reason=one-shot init; jitted encoders close over host copies
        self.proj = np.asarray(
            jax.random.rademacher(kp, (repetitions, dims, self.dproj)),
            np.float32) / np.sqrt(self.dproj)
        self.fde_dim = repetitions * self.buckets * self.dproj
        self._bit_weights = (1 << np.arange(ksim)).astype(np.int32)

    # -- host-side (numpy): exact, no padding needed ------------------------
    def _bucket_ids(self, tokens: np.ndarray) -> np.ndarray:
        """[R, T] bucket ids from sign bits of the gaussian projections."""
        # [R, ksim, D] x [T, D] -> [R, ksim, T]
        dots = np.einsum("rkd,td->rkt", self.gaussians, tokens)
        bits = (dots < 0).astype(np.int32)
        return np.einsum("rkt,k->rt", bits, self._bit_weights)

    def encode_doc(self, tokens: np.ndarray) -> np.ndarray:
        """[T, D] -> [fde_dim]. Per bucket: MEAN of assigned tokens; empty
        buckets take the hamming-nearest token (MUVERA fill)."""
        tokens = np.asarray(tokens, np.float32)
        ids = self._bucket_ids(tokens)  # [R, T]
        out = np.zeros((self.repetitions, self.buckets, self.dims), np.float32)
        for r in range(self.repetitions):
            counts = np.bincount(ids[r], minlength=self.buckets).astype(np.float32)
            np.add.at(out[r], ids[r], tokens)
            nz = counts > 0
            out[r][nz] /= counts[nz][:, None]
            if not nz.all():
                # hamming distance between bucket index bits and token bits
                empty = np.nonzero(~nz)[0]
                xor = empty[:, None] ^ ids[r][None, :]  # [E, T]
                ham = np.vectorize(lambda x: bin(x).count("1"))(xor)
                nearest = np.argmin(ham, axis=1)
                out[r][empty] = tokens[nearest]
        # per-repetition projection: [B, D] @ [D, dp]
        proj = np.einsum("rbd,rdp->rbp", out, self.proj)
        return proj.reshape(-1)

    def encode_query(self, tokens: np.ndarray) -> np.ndarray:
        """[Tq, D] -> [fde_dim]. SUM per bucket, no fill (paper asymmetry)."""
        tokens = np.asarray(tokens, np.float32)
        ids = self._bucket_ids(tokens)
        out = np.zeros((self.repetitions, self.buckets, self.dims), np.float32)
        for r in range(self.repetitions):
            np.add.at(out[r], ids[r], tokens)
        proj = np.einsum("rbd,rdp->rbp", out, self.proj)
        return proj.reshape(-1)


def maxsim_scores(query: np.ndarray, cand_tokens: np.ndarray,
                  cand_mask: np.ndarray) -> np.ndarray:
    """Exact late-interaction (Chamfer/MaxSim) on device.

    query [Tq, D]; cand_tokens [C, Tmax, D] zero-padded; cand_mask [C, Tmax].
    Returns [C] scores = sum over query tokens of max over doc tokens of the
    dot product (reference hnsw/search.go:927 rescore loop -> one einsum).
    With an active device mesh the candidate axis shards across it
    (``parallel.sharded_maxsim``) — the rescore tier's sequence-parallel
    analogue for long token sets.
    """
    import jax.numpy as jnp

    from weaviate_tpu.parallel.runtime import default_mesh

    mesh = default_mesh()
    if mesh is not None and cand_tokens.shape[0] >= 2 * mesh.size:
        from weaviate_tpu.parallel.sharded_search import sharded_maxsim
        from jax.sharding import NamedSharding, PartitionSpec as P
        from weaviate_tpu.parallel.mesh import SHARD_AXIS

        c = cand_tokens.shape[0]
        pad = (-c) % mesh.size
        if pad:
            cand_tokens = np.concatenate(
                [cand_tokens, np.zeros((pad, *cand_tokens.shape[1:]),
                                       np.float32)])
            cand_mask = np.concatenate(
                [cand_mask, np.zeros((pad, cand_mask.shape[1]), bool)])
        import jax

        toks = jax.device_put(
            cand_tokens.astype(np.float32),
            NamedSharding(mesh, P(SHARD_AXIS, None, None)))
        mask = jax.device_put(cand_mask,
                              NamedSharding(mesh, P(SHARD_AXIS, None)))
        # replication of the query rides sharded_maxsim's identity-keyed
        # cache (one upload per query batch, not per invocation)
        q = np.asarray(query, np.float32)
        # graftlint: allow[host-sync-in-hot-path] reason=final [C] score materialization for host rerank
        return np.asarray(sharded_maxsim(q, toks, mask, mesh=mesh))[:c]

    q = jnp.asarray(query, jnp.float32)
    c = jnp.asarray(cand_tokens, jnp.float32)
    m = jnp.asarray(cand_mask, bool)
    sims = jnp.einsum("qd,ctd->cqt", q, c, preferred_element_type=jnp.float32)
    sims = jnp.where(m[:, None, :], sims, -jnp.inf)
    best = jnp.max(sims, axis=2)  # [C, Tq]
    best = jnp.where(jnp.isfinite(best), best, 0.0)
    # graftlint: allow[host-sync-in-hot-path] reason=final [C] score materialization for host rerank
    return np.asarray(jnp.sum(best, axis=1))


class MultiVectorIndex(VectorIndex):
    """FDE candidate index + token store + exact MaxSim rescore tier."""

    def __init__(self, dims: int, config: Optional[MultiVectorIndexConfig] = None):
        self.config = config or MultiVectorIndexConfig()
        self.dims = dims
        self.metric = "dot"  # FDE similarity is inner product
        self.encoder = MuveraEncoder(
            dims, ksim=self.config.ksim, dproj=self.config.dproj,
            repetitions=self.config.repetitions)
        inner_cfg = FlatIndexConfig(
            distance="dot",
            initial_capacity=self.config.initial_capacity,
            precision=self.config.precision,
            flat_approx_recall=self.config.flat_approx_recall,
        )
        self.inner = FlatIndex(self.encoder.fde_dim, inner_cfg)
        # host token store for the exact rescore tier (doc_id -> [T, D])
        self._tokens: dict[int, np.ndarray] = {}

    multi_vector = True

    # -- writes -------------------------------------------------------------
    def add_batch_multi(self, doc_ids: np.ndarray,
                        token_sets: list[np.ndarray]) -> None:
        if len(doc_ids) == 0:
            return
        token_sets = [np.atleast_2d(np.asarray(t, np.float32))
                      for t in token_sets]
        # tokens BEFORE the candidate index: a racing search that sees the
        # new id in the FDE corpus must find its rescore tokens
        for d, t in zip(doc_ids, token_sets):
            self._tokens[int(d)] = t
        fdes = np.stack([self.encoder.encode_doc(t) for t in token_sets])
        self.inner.add_batch(np.asarray(doc_ids, np.int64), fdes)

    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        """Single-vector adds are degenerate token sets of size 1."""
        self.add_batch_multi(doc_ids, [v[None, :] if v.ndim == 1 else v
                                       for v in vectors])

    def delete(self, doc_ids: np.ndarray) -> None:
        self.inner.delete(doc_ids)
        for d in np.asarray(doc_ids).reshape(-1):
            self._tokens.pop(int(d), None)

    # -- search ---------------------------------------------------------------
    def search_multi(self, query_tokens: np.ndarray, k: int,
                     allow_list: Optional[np.ndarray] = None) -> SearchResult:
        """query_tokens [Tq, D] -> top-k by exact MaxSim over the FDE
        candidates (rescore_limit-wide)."""
        query_tokens = np.atleast_2d(np.asarray(query_tokens, np.float32))
        if query_tokens.shape[-1] != self.dims:
            raise ValueError(
                f"query token dims {query_tokens.shape[-1]} != {self.dims}")
        fde = self.encoder.encode_query(query_tokens)[None, :]
        cand_k = max(k, self.config.rescore_limit or 4 * k)
        cand_k = min(cand_k, max(1, self.inner.count()))
        res = self.inner.search(fde, cand_k, allow_list)
        cand = res.ids[0]
        cand = cand[cand >= 0]
        if len(cand) == 0:
            return SearchResult(ids=np.full((1, k), -1, np.int64),
                                dists=np.full((1, k), np.inf, np.float32))
        # a candidate may have been deleted between the FDE search and here
        sets = []
        kept = []
        for d in cand:
            t = self._tokens.get(int(d))
            if t is not None:
                sets.append(t)
                kept.append(int(d))
        cand = np.asarray(kept, np.int64)
        if len(cand) == 0:
            return SearchResult(ids=np.full((1, k), -1, np.int64),
                                dists=np.full((1, k), np.inf, np.float32))
        tmax = max(s.shape[0] for s in sets)
        toks = np.zeros((len(sets), tmax, self.dims), np.float32)
        mask = np.zeros((len(sets), tmax), bool)
        for i, s in enumerate(sets):
            toks[i, : s.shape[0]] = s
            mask[i, : s.shape[0]] = True
        scores = maxsim_scores(query_tokens, toks, mask)
        order = np.argsort(-scores, kind="stable")[:k]
        ids = np.full((1, k), -1, np.int64)
        d = np.full((1, k), np.inf, np.float32)
        ids[0, : len(order)] = cand[order]
        # present as a distance: negated MaxSim (lower = better)
        d[0, : len(order)] = -scores[order]
        return SearchResult(ids=ids, dists=d)

    def search(self, queries: np.ndarray, k: int,
               allow_list: Optional[np.ndarray] = None) -> SearchResult:
        """[B, D] single-vector queries (each = a 1-token set) or a single
        [Tq, D] token matrix via search_multi."""
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        outs = [self.search_multi(q[None, :], k, allow_list) for q in queries]
        return SearchResult(
            ids=np.concatenate([o.ids for o in outs]),
            dists=np.concatenate([o.dists for o in outs]),
        )

    def search_by_distance(self, queries, max_distance, allow_list=None,
                           limit: int = 1024):
        res = self.search(queries, min(limit, max(1, self.count())), allow_list)
        keep = res.dists <= max_distance
        return SearchResult(ids=np.where(keep, res.ids, -1),
                            dists=np.where(keep, res.dists, np.inf))

    # -- checkpoint ----------------------------------------------------------
    def save_vectors(self, path: str, meta: Optional[dict] = None) -> bool:
        """FDE corpus via the inner store + one token file — boot becomes
        O(bytes) instead of an O(corpus) re-encode through the FDE loop."""
        import os

        import msgpack

        self.inner.store.save(path, meta)
        tmp = path + ".tokens.tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb({
                "version": 1,
                "docs": [
                    {"d": d, "shape": list(t.shape), "data": t.tobytes()}
                    for d, t in self._tokens.items()
                ],
            }, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path + ".tokens")
        return True

    def load_vectors(self, path: str) -> Optional[dict]:
        import os

        import msgpack

        meta = self.inner.store.load(path)
        if meta is None:
            return None
        tok_path = path + ".tokens"
        if not os.path.exists(tok_path):
            return None  # half a checkpoint is no checkpoint
        try:
            with open(tok_path, "rb") as f:
                d = msgpack.unpackb(f.read(), raw=False)
            if d.get("version") != 1:
                return None
            self._tokens = {
                rec["d"]: np.frombuffer(rec["data"], np.float32)
                .reshape(rec["shape"]).copy()
                for rec in d["docs"]
            }
        except (OSError, ValueError, KeyError, TypeError, AttributeError):
            # torn/corrupt token sidecar: contract is "rebuild from source"
            return None
        return meta

    # -- bookkeeping ---------------------------------------------------------
    def count(self) -> int:
        return self.inner.count()

    @property
    def capacity(self) -> int:
        return self.inner.capacity

    def contains(self, doc_id: int) -> bool:
        return self.inner.contains(doc_id)

    # -- tiered residency (docs/tiering.md): the FDE corpus is the inner
    # FlatIndex, whose warm tier serves demoted searches exactly; the
    # token store for the rescore tier is host-side already. Pure
    # delegation keeps the budget ledger seeing the real HBM rent.
    @property
    def device_resident(self) -> bool:
        return self.inner.device_resident

    def hbm_bytes(self) -> int:
        return self.inner.hbm_bytes()

    def host_tier_bytes(self) -> int:
        return self.inner.host_tier_bytes()

    def demote_device(self) -> int:
        return self.inner.demote_device()

    def promote_device(self) -> int:
        return self.inner.promote_device()

    def stats(self) -> dict:
        return {
            "type": "multivector",
            "count": self.count(),
            "fde_dim": self.encoder.fde_dim,
            "token_dims": self.dims,
        }
