"""Flat (brute-force) TPU index.

Reference: ``adapters/repos/db/vector/flat/index.go:49``. There, flat search is
the slow fallback (scan LSM bucket, per-vector SIMD distance). On TPU it is the
*primary* fast path: the whole corpus lives in HBM and a query batch is one
fused masked-matmul + top_k (see SURVEY.md §7 slice 0 and BASELINE.md SIFT1M
config).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from weaviate_tpu.index.base import SearchResult, VectorIndex
from weaviate_tpu.index.store import DeviceVectorStore
from weaviate_tpu.ops.distance import MASK_DISTANCE, flat_search
from weaviate_tpu.ops.topk import masked_topk
from weaviate_tpu.schema.config import FlatIndexConfig


class FlatIndex(VectorIndex):
    def __init__(self, dims: int, config: Optional[FlatIndexConfig] = None):
        self.config = config or FlatIndexConfig()
        self.metric = self.config.distance
        self.store = DeviceVectorStore(
            dims,
            capacity=self.config.initial_capacity,
            normalized=(self.metric == "cosine"),
        )

    # -- VectorIndex ------------------------------------------------------
    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        self.store.put(doc_ids, vectors)

    def delete(self, doc_ids: np.ndarray) -> None:
        self.store.delete(doc_ids)

    def search(
        self,
        queries: np.ndarray,
        k: int,
        allow_list: Optional[np.ndarray] = None,
    ) -> SearchResult:
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        if queries.shape[-1] != self.store.dims:
            raise ValueError(
                f"query dims {queries.shape[-1]} != index dims {self.store.dims}"
            )
        qj = jnp.asarray(queries)
        if self.metric == "cosine":
            from weaviate_tpu.ops.distance import normalize

            qj = normalize(qj)
        allow = None
        if allow_list is not None:
            allow = _pad_mask(allow_list, self.store.capacity)
        chunk = self.config.search_chunk_size
        d, ids = flat_search(
            qj,
            self.store.corpus,
            k=k,
            metric=self.metric,
            valid_mask=self.store.valid_mask,
            allow_mask=allow,
            corpus_sqnorms=self.store.sqnorms if self.metric == "l2-squared" else None,
            chunk_size=chunk if self.store.capacity > chunk else 0,
            precision=self.config.precision,
        )
        return SearchResult(ids=np.asarray(ids), dists=np.asarray(d))

    def search_by_distance(
        self,
        queries: np.ndarray,
        max_distance: float,
        allow_list: Optional[np.ndarray] = None,
        limit: int = 1024,
    ) -> SearchResult:
        k = min(limit, max(1, self.store.live_count))
        res = self.search(queries, k, allow_list)
        keep = res.dists <= max_distance
        ids = np.where(keep, res.ids, -1)
        dists = np.where(keep, res.dists, np.float32(MASK_DISTANCE))
        return SearchResult(ids=ids, dists=dists)

    def count(self) -> int:
        return self.store.live_count

    @property
    def capacity(self) -> int:
        return self.store.capacity

    def contains(self, doc_id: int) -> bool:
        return self.store.contains(doc_id)

    def stats(self) -> dict:
        return {
            "type": "flat",
            "count": self.count(),
            "capacity": self.capacity,
            "metric": self.metric,
        }


def _pad_mask(mask: np.ndarray, capacity: int) -> jnp.ndarray:
    mask = np.asarray(mask, bool)
    if mask.shape[0] < capacity:
        mask = np.pad(mask, (0, capacity - mask.shape[0]))
    return jnp.asarray(mask[:capacity])
