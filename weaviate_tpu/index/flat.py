"""Flat (brute-force) TPU index.

Reference: ``adapters/repos/db/vector/flat/index.go:49``. There, flat search is
the slow fallback (scan LSM bucket, per-vector SIMD distance). On TPU it is the
*primary* fast path: the whole corpus lives in HBM and a query batch is one
fused masked-matmul + top_k (see SURVEY.md §7 slice 0 and BASELINE.md SIFT1M
config).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.index.base import (
    SearchResult,
    VectorIndex,
    run_tier_stable,
)
from weaviate_tpu.index.store import DeviceVectorStore
from weaviate_tpu.ops.distance import MASK_DISTANCE, flat_search
from weaviate_tpu.ops.topk import masked_topk
from weaviate_tpu.schema.config import FlatIndexConfig


def make_flat(dims: int, config: Optional[FlatIndexConfig] = None,
              raw_path: Optional[str] = None) -> VectorIndex:
    """Flat-index factory: raw HBM corpus, or code planes + rescore tier when
    a quantizer is configured (reference ``flat/index.go:49`` + ``quantizer.go``).
    ``raw_path`` places a disk16 originals memmap per index instance without
    mutating the (possibly shared) config."""
    config = config or FlatIndexConfig()
    if config.quantizer is not None and config.quantizer.enabled:
        return QuantizedFlatIndex(dims, config, raw_path=raw_path)
    return FlatIndex(dims, config)


class FlatIndex(VectorIndex):
    def __init__(self, dims: int, config: Optional[FlatIndexConfig] = None):
        from weaviate_tpu.parallel.runtime import default_mesh

        self.dims = dims
        self.config = config or FlatIndexConfig()
        self.metric = self.config.distance
        # Multi-chip: the corpus rows shard across the process mesh and
        # search runs as one SPMD program (reference scatter-gathers across
        # nodes instead, index.go:1928).
        self.store = DeviceVectorStore(
            dims,
            capacity=self.config.initial_capacity,
            normalized=(self.metric == "cosine"),
            mesh=default_mesh(),
        )

    # -- VectorIndex ------------------------------------------------------
    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        self.store.put(doc_ids, vectors)

    def delete(self, doc_ids: np.ndarray) -> None:
        self.store.delete(doc_ids)

    def search(
        self,
        queries: np.ndarray,
        k: int,
        allow_list: Optional[np.ndarray] = None,
        approx_recall: Optional[float] = None,
        est_selectivity: Optional[float] = None,
    ) -> SearchResult:
        """Top-k scan. ``approx_recall`` overrides the config knob (range
        queries force 0.0: approx selection may drop in-range rows, which
        breaks the search_by_distance contract rather than trading recall).
        ``est_selectivity`` is accepted for signature parity with the
        planner-aware HNSW path and ignored — a flat scan IS the exact
        plan."""
        # a tiering demote/promote between the residency check below and
        # the array access re-routes the query, never fails it
        return run_tier_stable(
            lambda: self._search_impl(queries, k, allow_list, approx_recall))

    def _search_impl(
        self,
        queries: np.ndarray,
        k: int,
        allow_list: Optional[np.ndarray] = None,
        approx_recall: Optional[float] = None,
    ) -> SearchResult:
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        if queries.shape[-1] != self.store.dims:
            raise ValueError(
                f"query dims {queries.shape[-1]} != index dims {self.store.dims}"
            )
        if approx_recall is None:
            approx_recall = self.config.flat_approx_recall
            if approx_recall < 0.0:
                # UNSET: follow the fleet-wide hot-reloadable default.
                # 0.0 means PINNED exact and never follows the override.
                from weaviate_tpu.utils.runtime_config import (
                    FLAT_APPROX_RECALL_DEFAULT,
                )

                approx_recall = FLAT_APPROX_RECALL_DEFAULT.get()
        if not self.store.device_resident:
            # WARM tier (tiering/): the corpus is demoted to host RAM —
            # serve exactly from there, never re-renting HBM per query
            from weaviate_tpu.index.hnsw.backend import host_store_topk

            d, ids = host_store_topk(
                self.store, self.metric, queries, k, allow_list)
            return SearchResult(ids=ids, dists=d)
        qj = jnp.asarray(queries)
        if self.metric == "cosine":
            from weaviate_tpu.ops.distance import normalize

            qj = normalize(qj)
        if self.store.mesh is not None:
            from weaviate_tpu.parallel.sharded_search import mesh_flat_topk

            d, ids = mesh_flat_topk(
                self.store, qj, k, self.metric, allow=allow_list,
                precision=self.config.precision,
                chunk_size=self.config.search_chunk_size,
                approx_recall=approx_recall,
            )
            # graftlint: allow[host-sync-in-hot-path] reason=final top-k materialization
            return SearchResult(ids=np.asarray(ids), dists=np.asarray(d))
        # one consistent device-state snapshot (concurrent writers swap it)
        corpus, valid, sqnorms = self.store.snapshot()
        cap = corpus.shape[0]
        allow = None
        if allow_list is not None:
            allow = _pad_mask(allow_list, cap)
        chunk = self.config.search_chunk_size
        # optional fused Pallas kernel (env-gated; see pallas_flat.py).
        # Taken only where its semantics match the request: bf16 is the
        # configured precision, approximate selection is permitted
        # (approx_recall=0.0 pins EXACT — range queries ride that), and k
        # is small enough for the kernel's unrolled extract-min loop.
        from weaviate_tpu.ops import pallas_flat

        if (self.metric == "l2-squared" and sqnorms is not None
                and pallas_flat.usable()
                and self.config.precision == "bf16"
                and approx_recall > 0.0 and k <= 64):
            m = valid if allow is None else (valid & allow)
            csz = min(chunk or cap, cap)
            # live candidate count (host-tracked; allowlist cardinality
            # counted on the host-side mask) sizes the kernel's fold so
            # its collision-loss bound holds against the REAL population,
            # not the padded capacity; power-of-4 bucketing keeps the
            # static arg from recompiling per write. With a filter the
            # true population is |valid & allow|, unknown host-side —
            # use the inclusion-exclusion LOWER bound max(live+|allow|-
            # cap, 1): fold sizing from an underestimate only ever
            # degrades toward exact (fold=1) selection, never past the
            # advertised loss bound
            live = self.store.live_count
            if allow_list is not None:
                allow_n = int(np.count_nonzero(
                    np.asarray(allow_list, bool)))
                live = max(1, live + allow_n - cap)
            if pallas_flat.fits(cap, csz):
                out = pallas_flat.try_flat_topk(
                    qj, corpus, sqnorms, m, k, chunk_size=csz,
                    live_rows=pallas_flat.bucket_live(live))
                if out is not None:
                    d, ids = out
                    return SearchResult(
                        # graftlint: allow[host-sync-in-hot-path] reason=final top-k materialization
                        ids=np.asarray(ids), dists=np.asarray(d))
        d, ids = flat_search(
            qj,
            corpus,
            k=k,
            metric=self.metric,
            valid_mask=valid,
            allow_mask=allow,
            corpus_sqnorms=sqnorms if self.metric == "l2-squared" else None,
            chunk_size=chunk if cap > chunk else 0,
            precision=self.config.precision,
            approx_recall=approx_recall,
        )
        # graftlint: allow[host-sync-in-hot-path] reason=final top-k materialization
        return SearchResult(ids=np.asarray(ids), dists=np.asarray(d))

    def search_by_distance(
        self,
        queries: np.ndarray,
        max_distance: float,
        allow_list: Optional[np.ndarray] = None,
        limit: int = 1024,
    ) -> SearchResult:
        k = min(limit, max(1, self.store.live_count))
        res = self.search(queries, k, allow_list, approx_recall=0.0)
        keep = res.dists <= max_distance
        ids = np.where(keep, res.ids, -1)
        dists = np.where(keep, res.dists, np.float32(MASK_DISTANCE))
        return SearchResult(ids=ids, dists=dists)

    def count(self) -> int:
        return self.store.live_count

    @property
    def capacity(self) -> int:
        return self.store.capacity

    def contains(self, doc_id: int) -> bool:
        return self.store.contains(doc_id)

    def save_vectors(self, path: str, meta: Optional[dict] = None) -> bool:
        self.store.save(path, meta)
        return True

    def load_vectors(self, path: str) -> Optional[dict]:
        return self.store.load(path)

    # -- tiered residency (docs/tiering.md) -------------------------------
    @property
    def device_resident(self) -> bool:
        return self.store.device_resident

    def hbm_bytes(self) -> int:
        return self.store.nbytes

    def host_tier_bytes(self) -> int:
        return self.store.host_bytes

    def demote_device(self) -> int:
        return self.store.detach()

    def promote_device(self) -> int:
        return self.store.attach()

    def stats(self) -> dict:
        s = {
            "type": "flat",
            "count": self.count(),
            "capacity": self.capacity,
            "metric": self.metric,
            "device_resident": self.store.device_resident,
        }
        per_shard = self.store.per_shard_live()
        if per_shard is not None:
            # mesh mode: surface the shard layout + feed the skew gauges
            from weaviate_tpu.monitoring.metrics import set_mesh_shard_gauges

            s["mesh_shards"] = len(per_shard)
            s["mesh_shard_rows"] = [int(x) for x in per_shard]
            set_mesh_shard_gauges(per_shard)
        return s


def _pad_mask(mask: np.ndarray, capacity: int) -> jnp.ndarray:
    mask = np.asarray(mask, bool)
    if mask.shape[0] < capacity:
        mask = np.pad(mask, (0, capacity - mask.shape[0]))
    return jnp.asarray(mask[:capacity])


def exact_rescore(
    queries: np.ndarray,
    cand_ids: np.ndarray,
    vectors: "HostVectorStore",
    metric: str,
    k: int,
) -> SearchResult:
    """Re-rank approximate candidates with exact fp32 distances on the host.

    Reference ``hnsw/search.go:184`` (shouldRescore): compressed search
    over-fetches, then the top candidates are re-scored against original
    vectors. cand_ids: [B, k'] device results (-1 = empty). The candidate
    sets are tiny (k' ~ 10-200) so host BLAS is the right tier — no HBM
    round-trip for the originals.
    """
    cand_ids = np.asarray(cand_ids)
    b, kp = cand_ids.shape
    safe = np.clip(cand_ids, 0, None)
    cand = vectors.get(safe.reshape(-1)).reshape(b, kp, -1)  # [B, k', D]
    q = np.asarray(queries, np.float32)
    if metric == "l2-squared":
        diff = q[:, None, :] - cand
        d = np.einsum("bkd,bkd->bk", diff, diff)
    elif metric in ("dot", "cosine"):
        ip = np.einsum("bd,bkd->bk", q, cand)
        d = -ip if metric == "dot" else 1.0 - ip
    elif metric == "manhattan":
        d = np.abs(q[:, None, :] - cand).sum(axis=-1)
    else:  # hamming over raw floats (reference hamming.go float variant)
        d = (q[:, None, :] != cand).sum(axis=-1).astype(np.float32)
    d = np.where(cand_ids < 0, np.float32(MASK_DISTANCE), d.astype(np.float32))
    k = min(k, kp)
    part = np.argpartition(d, k - 1, axis=1)[:, :k]
    pd = np.take_along_axis(d, part, axis=1)
    order = np.argsort(pd, axis=1, kind="stable")
    sel = np.take_along_axis(part, order, axis=1)
    out_d = np.take_along_axis(d, sel, axis=1)
    out_i = np.take_along_axis(cand_ids, sel, axis=1)
    out_i = np.where(out_d >= MASK_DISTANCE, -1, out_i)
    return SearchResult(ids=out_i, dists=out_d)


class QuantizedFlatIndex(VectorIndex):
    """Flat index over HBM-resident code planes with host-side rescore.

    Reference ``flat/index.go`` with BQ/SQ/RQ (``flat/quantizer.go``): codes
    live in the LSM 'vectors_compressed' bucket and distances are SIMD over
    codes; here codes are device arrays and distances are one MXU kernel per
    chunk (``ops/quantized.py``). Storage, fit policy, code search and the
    rescore tier all live in ``hnsw.backend.QuantizedBackend`` — this class
    is the VectorIndex adapter over it (same backend HNSW traversal uses).
    """

    def __init__(self, dims: int, config: FlatIndexConfig,
                 raw_path: Optional[str] = None):
        from weaviate_tpu.index.hnsw.backend import QuantizedBackend

        self.config = config
        self.metric = config.distance
        self.dims = dims
        self.backend = QuantizedBackend(dims, config, raw_path=raw_path)

    @property
    def quantizer(self):
        return self.backend.quantizer

    # -- VectorIndex ------------------------------------------------------
    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        self.backend.put(np.asarray(doc_ids, np.int64), vectors)

    def delete(self, doc_ids: np.ndarray) -> None:
        self.backend.delete(doc_ids)

    def search(
        self,
        queries: np.ndarray,
        k: int,
        allow_list: Optional[np.ndarray] = None,
        est_selectivity: Optional[float] = None,
    ) -> SearchResult:
        queries = np.atleast_2d(np.asarray(queries, np.float32))
        if queries.shape[-1] != self.dims:
            raise ValueError(
                f"query dims {queries.shape[-1]} != index dims {self.dims}"
            )
        d, ids = run_tier_stable(
            lambda: self.backend.flat_topk(queries, k, allow_list))
        return SearchResult(ids=ids, dists=d)

    def search_by_distance(
        self,
        queries: np.ndarray,
        max_distance: float,
        allow_list: Optional[np.ndarray] = None,
        limit: int = 1024,
    ) -> SearchResult:
        k = min(limit, max(1, self.count()))
        res = self.search(queries, k, allow_list)
        keep = res.dists <= max_distance
        return SearchResult(
            ids=np.where(keep, res.ids, -1),
            dists=np.where(keep, res.dists, np.float32(MASK_DISTANCE)),
        )

    def count(self) -> int:
        return self.backend.originals.live_count

    @property
    def capacity(self) -> int:
        return self.backend.capacity

    def contains(self, doc_id: int) -> bool:
        return self.backend.contains(doc_id)

    # -- tiered residency (docs/tiering.md) -------------------------------
    @property
    def device_resident(self) -> bool:
        return self.backend.device_resident

    def hbm_bytes(self) -> int:
        return self.backend.hbm_bytes()

    def host_tier_bytes(self) -> int:
        return self.backend.host_tier_bytes()

    def demote_device(self) -> int:
        return self.backend.demote_device()

    def promote_device(self) -> int:
        return self.backend.promote_device()

    def stats(self) -> dict:
        return {
            "type": "flat",
            "quantizer": self.quantizer.kind,
            "fitted": self.quantizer.fitted,
            "count": self.count(),
            "capacity": self.capacity,
            "metric": self.metric,
            "device_resident": self.backend.device_resident,
        }
