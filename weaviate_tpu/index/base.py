"""The vector-index interface every backend implements.

Mirrors the reference's ``adapters/repos/db/vector_index.go:25`` (VectorIndex:
Add/AddBatch/Delete/SearchByVector/SearchByVectorDistance/Flush/Drop/
PostStartup/...), with one deliberate TPU-first change: **every method is
batched**. The reference's per-vector ``Add(id, vec)`` / per-candidate
``Distance`` calls would serialize the device; here the unit of work is a
batch of ids/vectors/queries.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class SearchResult:
    """Top-k result for a batch of queries: ids[b, k] (-1 = empty), dists[b, k]."""

    ids: np.ndarray
    dists: np.ndarray


class VectorIndex(abc.ABC):
    """Batched ANN index over internal doc ids (uint64 monotonic per shard)."""

    multi_vector: bool = False
    # whether search() accepts a resident FilterPlane as ``allow_list``
    # (query/planner/planes.py); callers resolve the plane's host bitmap
    # for indexes that don't
    supports_filter_planes: bool = False

    @abc.abstractmethod
    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        """Insert/overwrite vectors for the given internal doc ids."""

    @abc.abstractmethod
    def delete(self, doc_ids: np.ndarray) -> None:
        """Remove ids (tombstone semantics — slots masked, space reclaimed later)."""

    @abc.abstractmethod
    def search(
        self,
        queries: np.ndarray,
        k: int,
        allow_list: Optional[np.ndarray] = None,
        est_selectivity: Optional[float] = None,
    ) -> SearchResult:
        """Batched top-k by vector. ``allow_list``: bool mask over doc ids
        (or a resident FilterPlane where the index supports them).
        ``est_selectivity``: the inverted index's sketch estimate for the
        filter — explainability payload for planner-routed indexes, ignored
        by the rest."""

    @abc.abstractmethod
    def search_by_distance(
        self,
        queries: np.ndarray,
        max_distance: float,
        allow_list: Optional[np.ndarray] = None,
        limit: int = 1024,
    ) -> SearchResult:
        """All results within max_distance (reference SearchByVectorDistance)."""

    @abc.abstractmethod
    def count(self) -> int:
        """Live (non-deleted) vector count."""

    @property
    @abc.abstractmethod
    def capacity(self) -> int:
        """Current padded device capacity (doc-id space size)."""

    def contains(self, doc_id: int) -> bool:
        raise NotImplementedError

    def flush(self) -> None:  # durability hook; storage owns real persistence
        pass

    # -- device-state checkpoint (shard boot = load + delta replay, not a
    # full object-store rebuild; reference hnsw/startup.go commit-log role)
    def save_vectors(self, path: str, meta: Optional[dict] = None) -> bool:
        """Persist the raw vector tier; False = unsupported by this index."""
        return False

    def load_vectors(self, path: str) -> Optional[dict]:
        """Restore the raw vector tier; returns saved meta, None = no/bad
        checkpoint (or unsupported) — caller falls back to full rebuild."""
        return None

    def drop(self) -> None:
        pass

    # -- tiered residency (tiering/ warm tier; docs/tiering.md) -----------
    # Default: an index type with no device arrays (or one that cannot
    # demote them) reports zero HBM rent and stays "resident" — the
    # controller then only ever cold-releases its whole shard.
    @property
    def device_resident(self) -> bool:
        """False while this index's device arrays are demoted to host."""
        return True

    def hbm_bytes(self) -> int:
        """Current HBM rent (0 while demoted / for host-only indexes)."""
        return 0

    def host_tier_bytes(self) -> int:
        """Host-RAM rent of demoted device arrays (warm tier)."""
        return 0

    def demote_device(self) -> int:
        """Move device arrays to host RAM (warm tier); returns HBM bytes
        released. Callers MUST feed the returned delta to the tiering
        accountant (graftlint rule ``device-array-leak``)."""
        return 0

    def promote_device(self) -> int:
        """Re-upload demoted arrays; returns HBM bytes charged. Same
        accountant contract as :meth:`demote_device`."""
        return 0

    def stats(self) -> dict:
        return {"count": self.count(), "capacity": self.capacity}


def run_tier_stable(fn):
    """Run a search closure, retrying when a residency flip lands between
    its tier check and the array access (``ResidencyMoved``). Either tier
    can serve any query, so a concurrent demote/promote must re-route the
    request, never fail it. Two retries bound the pathological case of a
    flip landing on every attempt."""
    from weaviate_tpu.compression.store import ResidencyMoved

    for _ in range(2):
        try:
            return fn()
        except ResidencyMoved:
            continue
    return fn()
