"""Geo index: vectorized haversine range queries over coordinate columns.

Reference: ``adapters/repos/db/vector/geo/geo.go`` wraps an HNSW with a
geo-distance distancer per geo property and answers
``WithinGeoRange`` via iterative radius-widening kNN. That design exists
because the reference's scan is a per-vector SIMD call; on this
architecture the idiomatic form is columnar: (id, lat, lon) arrays and ONE
vectorized haversine per query — exact (no ef/recall knob), branch-free,
and ~1M rows/ms on host SIMD with a jit device path beyond that. The
columnar filter engine (``inverted/columnar.py``) embeds the same kernel;
this class is the standalone per-property index the reference's component
maps to (``shard geo properties``, ``geo_props.go``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# beyond this many points, evaluation moves to the device (one [N] kernel)
_DEVICE_CUTOFF = 2_000_000

EARTH_RADIUS_M = 6371088.0


def haversine_m(lat0: float, lon0: float, lat: np.ndarray,
                lon: np.ndarray) -> np.ndarray:
    """Great-circle distance in meters (reference ``geo_spatial.go``)."""
    p0 = np.radians(lat0)
    p1 = np.radians(lat)
    dp = np.radians(lat - lat0)
    dl = np.radians(lon - lon0)
    a = np.sin(dp / 2.0) ** 2 + np.cos(p0) * np.cos(p1) * np.sin(dl / 2.0) ** 2
    return 2.0 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


class GeoIndex:
    """Per-property geo point set with range + kNN queries."""

    def __init__(self):
        self._ids = np.empty(16, np.int64)
        self._lat = np.empty(16, np.float64)
        self._lon = np.empty(16, np.float64)
        self._valid = np.zeros(16, bool)
        self._n = 0
        self._row_of: dict[int, int] = {}  # doc -> latest live row

    def add(self, doc_id: int, lat: float, lon: float) -> None:
        doc_id = int(doc_id)
        prev = self._row_of.get(doc_id)
        if prev is not None:
            # re-add/update: the old coordinates must stop matching
            self._valid[prev] = False
        if self._n == len(self._ids):
            self._ids = np.concatenate([self._ids, np.empty_like(self._ids)])
            self._lat = np.concatenate([self._lat, np.empty_like(self._lat)])
            self._lon = np.concatenate([self._lon, np.empty_like(self._lon)])
            self._valid = np.concatenate(
                [self._valid, np.zeros_like(self._valid)])
        self._ids[self._n] = doc_id
        self._lat[self._n] = lat
        self._lon[self._n] = lon
        self._valid[self._n] = True
        self._row_of[doc_id] = self._n
        self._n += 1

    def add_batch(self, doc_ids: np.ndarray, lats: np.ndarray,
                  lons: np.ndarray) -> None:
        for d, la, lo in zip(doc_ids, lats, lons):
            self.add(int(d), float(la), float(lo))

    def delete(self, doc_id: int) -> None:
        row = self._row_of.pop(int(doc_id), None)
        if row is not None:
            self._valid[row] = False

    def __len__(self) -> int:
        return len(self._row_of)

    def _dists(self, lat: float, lon: float) -> tuple[np.ndarray, np.ndarray]:
        ids = self._ids[: self._n]
        if self._n >= _DEVICE_CUTOFF:
            import jax.numpy as jnp

            la = jnp.asarray(self._lat[: self._n])
            lo = jnp.asarray(self._lon[: self._n])
            p0 = np.radians(lat)
            dp = jnp.radians(la - lat)
            dl = jnp.radians(lo - lon)
            a = (jnp.sin(dp / 2.0) ** 2
                 + np.cos(p0) * jnp.cos(jnp.radians(la))
                 * jnp.sin(dl / 2.0) ** 2)
            d = 2.0 * EARTH_RADIUS_M * jnp.arcsin(
                jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
            # graftlint: allow[host-sync-in-hot-path] reason=single [N] readback feeding the host radius filter
            d = np.asarray(d)
        else:
            d = haversine_m(lat, lon, self._lat[: self._n],
                            self._lon[: self._n])
        return ids, d

    def within_range(self, lat: float, lon: float,
                     max_distance_m: float) -> np.ndarray:
        """Doc ids within the radius (sorted ascending, live rows only)."""
        if self._n == 0:
            return np.empty(0, np.int64)
        ids, d = self._dists(lat, lon)
        hit = ids[(d <= max_distance_m) & self._valid[: self._n]]
        return np.unique(hit)

    def knn(self, lat: float, lon: float, k: int
            ) -> tuple[np.ndarray, np.ndarray]:
        """(ids, meters) of the k nearest live points."""
        if self._n == 0:
            return np.empty(0, np.int64), np.empty(0, np.float64)
        ids, d = self._dists(lat, lon)
        d = np.where(self._valid[: self._n], d, np.inf)
        order = np.argsort(d, kind="stable")[:k]
        order = order[np.isfinite(d[order])]
        return ids[order].astype(np.int64), d[order]
