"""The provider catalog: every API-backed module the reference ships.

Reference: one Go package per provider under ``modules/`` (67 total); the
clients differ mainly in endpoint, auth header, default model, and which of
four or five wire formats they clone. Here that variation is data
(``ProviderSpec`` rows) over the shared capability classes in
``api_provider.py``. Local/offline modules (contextionary, bigram, dummies,
transformers pipelines, spellcheck) live in ``local_text.py`` /
``extras.py``; storage-backed modules (backup-*, offload-s3, usage-*) are
part of the backup/offload subsystem.
"""

from __future__ import annotations

from typing import Optional

from weaviate_tpu.modules.api_provider import (
    APIGenerative,
    APIMultiModal,
    APIMultiVector,
    APIReranker,
    APIVectorizer,
    ProviderSpec,
    Transport,
)

S = ProviderSpec

TEXT2VEC_SPECS = [
    S("text2vec-openai", "openai", "https://api.openai.com/v1/embeddings",
      "OPENAI_APIKEY", model="text-embedding-3-small", dims=1536),
    S("text2vec-cohere", "cohere", "https://api.cohere.ai/v1/embed",
      "COHERE_APIKEY", model="embed-multilingual-v3.0", dims=1024),
    S("text2vec-voyageai", "openai", "https://api.voyageai.com/v1/embeddings",
      "VOYAGEAI_APIKEY", model="voyage-3", dims=1024),
    S("text2vec-jinaai", "openai", "https://api.jina.ai/v1/embeddings",
      "JINAAI_APIKEY", model="jina-embeddings-v3", dims=1024),
    S("text2vec-mistral", "openai", "https://api.mistral.ai/v1/embeddings",
      "MISTRAL_APIKEY", model="mistral-embed", dims=1024),
    S("text2vec-huggingface", "huggingface",
      "https://api-inference.huggingface.co/pipeline/feature-extraction/{model}",
      "HUGGINGFACE_APIKEY",
      model="sentence-transformers/all-MiniLM-L6-v2", dims=384),
    S("text2vec-ollama", "ollama", "http://localhost:11434/api/embed",
      auth="none", model="nomic-embed-text", dims=768),
    S("text2vec-google", "google",
      "https://us-central1-aiplatform.googleapis.com/v1/publishers/google/"
      "models/{model}:predict",
      "GOOGLE_APIKEY", model="textembedding-gecko@003", dims=768),
    S("text2vec-aws", "bedrock", "http://localhost:9018/bedrock/embed",
      "AWS_ACCESS_KEY", model="amazon.titan-embed-text-v2:0", dims=1024),
    S("text2vec-databricks", "openai", "http://localhost:9020/serving/embed",
      "DATABRICKS_TOKEN", dims=0),
    S("text2vec-nvidia", "openai",
      "https://integrate.api.nvidia.com/v1/embeddings",
      "NVIDIA_APIKEY", model="nvidia/nv-embed-v1", dims=4096),
    S("text2vec-octoai", "openai", "https://text.octoai.run/v1/embeddings",
      "OCTOAI_APIKEY", model="thenlper/gte-large", dims=1024),
    S("text2vec-weaviate", "openai",
      "https://api.embedding.weaviate.io/v1/embeddings",
      "WEAVIATE_APIKEY", model="Snowflake/snowflake-arctic-embed-m-v1.5",
      dims=768),
    S("text2vec-gpt4all", "local", "http://localhost:4891/vectorize",
      auth="none", dims=384),
]

GENERATIVE_SPECS = [
    S("generative-openai", "openai",
      "https://api.openai.com/v1/chat/completions",
      "OPENAI_APIKEY", model="gpt-4o-mini"),
    S("generative-anthropic", "anthropic",
      "https://api.anthropic.com/v1/messages",
      "ANTHROPIC_APIKEY", auth="x-api-key",
      model="claude-3-5-sonnet-latest"),
    S("generative-cohere", "cohere", "https://api.cohere.ai/v1/chat",
      "COHERE_APIKEY", model="command-r-plus"),
    S("generative-mistral", "openai",
      "https://api.mistral.ai/v1/chat/completions",
      "MISTRAL_APIKEY", model="mistral-large-latest"),
    S("generative-google", "google",
      "https://generativelanguage.googleapis.com/v1beta/models/"
      "{model}:generateContent",
      "GOOGLE_APIKEY", auth="header:x-goog-api-key",
      model="gemini-1.5-flash"),
    S("generative-ollama", "ollama", "http://localhost:11434/api/generate",
      auth="none", model="llama3.1"),
    S("generative-aws", "bedrock", "http://localhost:9018/bedrock/generate",
      "AWS_ACCESS_KEY", model="anthropic.claude-3-sonnet"),
    S("generative-anyscale", "openai",
      "https://api.endpoints.anyscale.com/v1/chat/completions",
      "ANYSCALE_APIKEY", model="meta-llama/Meta-Llama-3-70B-Instruct"),
    S("generative-databricks", "openai",
      "http://localhost:9020/serving/chat", "DATABRICKS_TOKEN"),
    S("generative-friendliai", "openai",
      "https://api.friendli.ai/serverless/v1/chat/completions",
      "FRIENDLI_TOKEN", model="meta-llama-3.1-70b-instruct"),
    S("generative-nvidia", "openai",
      "https://integrate.api.nvidia.com/v1/chat/completions",
      "NVIDIA_APIKEY", model="nvidia/llama-3.1-nemotron-70b-instruct"),
    S("generative-octoai", "openai",
      "https://text.octoai.run/v1/chat/completions",
      "OCTOAI_APIKEY", model="meta-llama-3.1-70b-instruct"),
    S("generative-xai", "openai", "https://api.x.ai/v1/chat/completions",
      "XAI_APIKEY", model="grok-2-latest"),
    S("generative-contextualai", "openai",
      "https://api.contextual.ai/v1/generate",
      "CONTEXTUALAI_APIKEY", model="v1"),
]

RERANKER_SPECS = [
    S("reranker-cohere", "cohere", "https://api.cohere.ai/v1/rerank",
      "COHERE_APIKEY", model="rerank-v3.5"),
    S("reranker-voyageai", "cohere", "https://api.voyageai.com/v1/rerank",
      "VOYAGEAI_APIKEY", model="rerank-2"),
    S("reranker-jinaai", "cohere", "https://api.jina.ai/v1/rerank",
      "JINAAI_APIKEY", model="jina-reranker-v2-base-multilingual"),
    S("reranker-nvidia", "cohere",
      "https://ai.api.nvidia.com/v1/retrieval/nvidia/reranking",
      "NVIDIA_APIKEY", model="nvidia/rerank-qa-mistral-4b"),
    S("reranker-contextualai", "cohere",
      "https://api.contextual.ai/v1/rerank",
      "CONTEXTUALAI_APIKEY", model="ctxl-rerank-en-v1"),
]

MULTI2VEC_SPECS = [
    # self-hosted sidecar contract (reference CLIP_INFERENCE_API etc.)
    S("multi2vec-clip", "local", "http://localhost:9090/vectorize",
      auth="none", dims=512),
    S("multi2vec-bind", "local", "http://localhost:9091/vectorize",
      auth="none", dims=1024),
    S("img2vec-neural", "local", "http://localhost:9092/vectorize",
      auth="none", dims=512),
    S("multi2vec-cohere", "cohere", "https://api.cohere.ai/v1/embed",
      "COHERE_APIKEY", model="embed-multilingual-v3.0", dims=1024),
    S("multi2vec-google", "google",
      "https://us-central1-aiplatform.googleapis.com/v1/publishers/google/"
      "models/{model}:predict",
      "GOOGLE_APIKEY", model="multimodalembedding@001", dims=1408),
    S("multi2vec-jinaai", "openai", "https://api.jina.ai/v1/embeddings",
      "JINAAI_APIKEY", model="jina-clip-v2", dims=1024),
    S("multi2vec-voyageai", "openai",
      "https://api.voyageai.com/v1/multimodalembeddings",
      "VOYAGEAI_APIKEY", model="voyage-multimodal-3", dims=1024),
    S("multi2vec-nvidia", "openai",
      "https://integrate.api.nvidia.com/v1/embeddings",
      "NVIDIA_APIKEY", model="nvidia/nvclip", dims=1024),
    S("multi2vec-aws", "bedrock", "http://localhost:9018/bedrock/embed",
      "AWS_ACCESS_KEY", model="amazon.titan-embed-image-v1", dims=1024),
]

MULTIVEC_SPECS = [
    S("text2multivec-jinaai", "openai", "https://api.jina.ai/v1/embeddings",
      "JINAAI_APIKEY", model="jina-colbert-v2", dims=128,
      extra={"return_multivector": True}),
    S("multi2multivec-jinaai", "openai",
      "https://api.jina.ai/v1/embeddings",
      "JINAAI_APIKEY", model="jina-colbert-v2", dims=128,
      extra={"return_multivector": True}),
    S("multi2multivec-weaviate", "openai",
      "https://api.embedding.weaviate.io/v1/multivector",
      "WEAVIATE_APIKEY", dims=128,
      extra={"return_multivector": True}),
]


def register_api_providers(reg, transport: Optional[Transport] = None) -> None:
    """Instantiate the full API-provider catalog into ``reg``. A custom
    ``transport`` (tests, proxies) applies to every provider."""
    for spec in TEXT2VEC_SPECS:
        reg.register(APIVectorizer(spec, transport))
    for spec in GENERATIVE_SPECS:
        reg.register(APIGenerative(spec, transport))
    for spec in RERANKER_SPECS:
        reg.register(APIReranker(spec, transport))
    for spec in MULTI2VEC_SPECS:
        reg.register(APIMultiModal(spec, transport))
    for spec in MULTIVEC_SPECS:
        reg.register(APIMultiVector(spec, transport))
