"""text2vec-transformers: local HuggingFace encoder (gated on cached weights).

Reference: ``modules/text2vec-transformers`` talks to a sidecar inference
container; here the model runs in-process (torch CPU / transformers are baked
into the image). Zero-egress: ``local_files_only=True`` — if the weights are
not already cached the module raises ``ModuleNotAvailable`` at init and the
registry simply does not offer it (the reference behaves the same when the
sidecar is down).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.modules.base import ModuleNotAvailable, Vectorizer

DEFAULT_MODEL = "sentence-transformers/all-MiniLM-L6-v2"


class TransformersVectorizer(Vectorizer):
    name = "text2vec-transformers"

    def __init__(self, model_name: str = DEFAULT_MODEL, max_length: int = 256):
        self.model_name = model_name
        self.max_length = max_length
        self._model = None
        self._tokenizer = None

    def _load(self):
        if self._model is not None:
            return
        try:
            import torch  # noqa: F401
            from transformers import AutoModel, AutoTokenizer

            self._tokenizer = AutoTokenizer.from_pretrained(
                self.model_name, local_files_only=True
            )
            self._model = AutoModel.from_pretrained(
                self.model_name, local_files_only=True
            )
            self._model.eval()
            self.dims = int(self._model.config.hidden_size)
        except Exception as e:  # missing weights, no torch, etc.
            raise ModuleNotAvailable(
                f"text2vec-transformers: model {self.model_name!r} not "
                f"available locally ({e})"
            ) from e

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        self._load()
        import torch

        enc = self._tokenizer(
            list(texts), padding=True, truncation=True,
            max_length=self.max_length, return_tensors="pt",
        )
        with torch.no_grad():
            out = self._model(**enc).last_hidden_state  # [n, t, h]
        mask = enc["attention_mask"].unsqueeze(-1).float()
        pooled = (out * mask).sum(1) / mask.sum(1).clamp(min=1e-9)
        vecs = torch.nn.functional.normalize(pooled, dim=-1).numpy()
        return np.asarray(vecs, np.float32)
