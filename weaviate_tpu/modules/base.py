"""Module SPI: the pluggable model-provider interface.

Reference: ``entities/modulecapabilities/module.go:45`` + the runtime registry
``usecases/modules/modules.go:45``. A module declares capabilities; the
registry wires them into the write path (vectorize-on-import), the query path
(nearText → query vector), and additional properties (rerank, generate).

The reference's 67 modules mostly call external inference HTTP APIs; in this
zero-egress build the in-tree providers are local (hash-based vectorizer,
transformers when weights are cached, lexical reranker, template generative) —
the SPI is the parity surface, providers are swappable.
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Sequence

import numpy as np


class Module(abc.ABC):
    """Base module: name + capability discovery via isinstance checks."""

    name: str = "module"

    def init(self, config: Optional[dict] = None) -> None:
        """Late init hook (reference InitExtension/InitVectorizer)."""

    def meta(self) -> dict:
        return {"name": self.name, "type": self.module_type()}

    def module_type(self) -> str:
        kinds = []
        if isinstance(self, Vectorizer):
            kinds.append("text2vec")
        if isinstance(self, Reranker):
            kinds.append("reranker")
        if isinstance(self, Generative):
            kinds.append("generative")
        return "+".join(kinds) or "extension"


class Vectorizer(Module):
    """text2vec capability (reference ``modulecapabilities/vectorizer.go``)."""

    dims: int = 0

    @abc.abstractmethod
    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        """Batch-embed texts → [n, dims] float32."""

    def vectorize_query(self, text: str) -> np.ndarray:
        """Query-time embedding (some providers use asymmetric encodings)."""
        return self.vectorize([text])[0]

    def texts_from_object(self, properties: dict, schema_props: Optional[list] = None) -> str:
        """Concatenate vectorizable text props (reference vectorizer behavior:
        lowercased prop name + value, sorted by prop name)."""
        parts = []
        for name in sorted(properties):
            v = properties[name]
            if isinstance(v, str):
                parts.append(v)
            elif isinstance(v, list) and v and isinstance(v[0], str):
                parts.extend(v)
        return " ".join(parts)


class Reranker(Module):
    """reranker capability (reference ``modulecapabilities/reranker.go``)."""

    @abc.abstractmethod
    def rerank(self, query: str, documents: Sequence[str]) -> list[float]:
        """Relevance score per document (higher is better)."""


class Generative(Module):
    """generative capability (reference ``modulecapabilities/generative.go``)."""

    @abc.abstractmethod
    def generate(
        self,
        prompt: str,
        context_documents: Sequence[str],
        grouped: bool = False,
    ) -> str:
        """Produce an answer from the prompt + retrieved context."""

    def generate_single(self, prompt_template: str, properties: dict) -> str:
        """singlePrompt: fill ``{prop}`` placeholders from the object's
        properties, then generate. Part of the SPI so providers can override
        (the reference's singlePrompt templating happens module-side)."""
        out = prompt_template
        for k, v in properties.items():
            out = out.replace("{" + k + "}", str(v))
        return self.generate(out, [])


class ModuleNotAvailable(RuntimeError):
    """Raised when a provider's backing model/service is unavailable
    (e.g. transformers weights not cached in a zero-egress environment)."""
