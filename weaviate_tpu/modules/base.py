"""Module SPI: the pluggable model-provider interface.

Reference: ``entities/modulecapabilities/module.go:45`` + the runtime registry
``usecases/modules/modules.go:45``. A module declares capabilities; the
registry wires them into the write path (vectorize-on-import), the query path
(nearText → query vector), and additional properties (rerank, generate).

The reference's 67 modules mostly call external inference HTTP APIs; in this
zero-egress build the in-tree providers are local (hash-based vectorizer,
transformers when weights are cached, lexical reranker, template generative) —
the SPI is the parity surface, providers are swappable.
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Sequence

import numpy as np


class Module(abc.ABC):
    """Base module: name + capability discovery via isinstance checks."""

    name: str = "module"

    def init(self, config: Optional[dict] = None) -> None:
        """Late init hook (reference InitExtension/InitVectorizer)."""

    def meta(self) -> dict:
        return {"name": self.name, "type": self.module_type()}

    def module_type(self) -> str:
        kinds = []
        if isinstance(self, MultiVectorVectorizer):
            kinds.append("text2multivec")
        elif isinstance(self, MultiModalVectorizer):
            kinds.append("multi2vec")
        elif isinstance(self, Vectorizer):
            kinds.append("text2vec")
        if isinstance(self, Reranker):
            kinds.append("reranker")
        if isinstance(self, Generative):
            kinds.append("generative")
        if isinstance(self, QnA):
            kinds.append("qna")
        if isinstance(self, Summarizer):
            kinds.append("sum")
        if isinstance(self, NERTagger):
            kinds.append("ner")
        if isinstance(self, SpellChecker):
            kinds.append("spellcheck")
        return "+".join(kinds) or "extension"


class Vectorizer(Module):
    """text2vec capability (reference ``modulecapabilities/vectorizer.go``)."""

    dims: int = 0

    @abc.abstractmethod
    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        """Batch-embed texts → [n, dims] float32."""

    def vectorize_query(self, text: str) -> np.ndarray:
        """Query-time embedding (some providers use asymmetric encodings)."""
        return self.vectorize([text])[0]

    def texts_from_object(self, properties: dict, schema_props: Optional[list] = None) -> str:
        """Concatenate vectorizable text props (reference vectorizer behavior:
        lowercased prop name + value, sorted by prop name)."""
        parts = []
        for name in sorted(properties):
            v = properties[name]
            if isinstance(v, str):
                parts.append(v)
            elif isinstance(v, list) and v and isinstance(v[0], str):
                parts.extend(v)
        return " ".join(parts)


class Reranker(Module):
    """reranker capability (reference ``modulecapabilities/reranker.go``)."""

    @abc.abstractmethod
    def rerank(self, query: str, documents: Sequence[str]) -> list[float]:
        """Relevance score per document (higher is better)."""


class Generative(Module):
    """generative capability (reference ``modulecapabilities/generative.go``)."""

    @abc.abstractmethod
    def generate(
        self,
        prompt: str,
        context_documents: Sequence[str],
        grouped: bool = False,
    ) -> str:
        """Produce an answer from the prompt + retrieved context."""

    def generate_single(self, prompt_template: str, properties: dict) -> str:
        """singlePrompt: fill ``{prop}`` placeholders from the object's
        properties, then generate. Part of the SPI so providers can override
        (the reference's singlePrompt templating happens module-side)."""
        out = prompt_template
        for k, v in properties.items():
            out = out.replace("{" + k + "}", str(v))
        return self.generate(out, [])


class MultiModalVectorizer(Vectorizer):
    """multi2vec capability: text + image (+ other media) into one space
    (reference ``modules/multi2vec-*``; fusion weights per class config)."""

    def vectorize_image(self, images_b64: Sequence[str]) -> np.ndarray:
        """Batch-embed base64 images → [n, dims] float32."""
        raise ModuleNotAvailable(f"{self.name}: image vectorization backend"
                                 " not configured")

    def fuse(self, vectors: Sequence[np.ndarray],
             weights: Optional[Sequence[float]] = None) -> np.ndarray:
        """Weighted-mean fusion of per-media vectors (reference
        multi2vec CalculateVector weighted average)."""
        vs = np.stack([np.asarray(v, np.float32) for v in vectors])
        w = (np.asarray(weights, np.float32)
             if weights is not None else np.ones(len(vs), np.float32))
        w = w / max(float(w.sum()), 1e-9)
        out = (vs * w[:, None]).sum(axis=0)
        n = float(np.linalg.norm(out))
        return out / n if n > 0 else out


class MultiVectorVectorizer(Module):
    """text2multivec capability: ColBERT-style token-vector sets, consumed
    by the MUVERA multivector index (reference ``text2multivec-jinaai``,
    ``multi2multivec-*``)."""

    dims: int = 0

    def vectorize_multi(self, texts: Sequence[str]) -> list[np.ndarray]:
        """Batch-embed texts → list of [tokens_i, dims] float32 arrays."""
        raise ModuleNotAvailable(f"{self.name}: multivector backend"
                                 " not configured")


class QnA(Module):
    """Extractive/abstractive question answering over retrieved objects
    (reference ``modules/qna-*``; GraphQL ``ask`` argument)."""

    @abc.abstractmethod
    def answer(self, question: str, context: str) -> dict:
        """→ {"answer": str|None, "certainty": float, "start": int,
        "end": int} (absent positions = -1 for abstractive providers)."""


class Summarizer(Module):
    """Property summarization (reference ``modules/sum-transformers``;
    ``_additional { summary }``)."""

    @abc.abstractmethod
    def summarize(self, text: str) -> str: ...


class NERTagger(Module):
    """Named-entity recognition over properties (reference
    ``modules/ner-transformers``; ``_additional { tokens }``)."""

    @abc.abstractmethod
    def tag(self, text: str) -> list[dict]:
        """→ [{"entity": label, "word": str, "start": int, "end": int,
        "certainty": float}]."""


class SpellChecker(Module):
    """Query spellcheck (reference ``modules/text-spellcheck``; corrects
    nearText concepts before vectorization)."""

    @abc.abstractmethod
    def check(self, text: str) -> dict:
        """→ {"original": str, "corrected": str, "changes": [...]}"""


class ModuleNotAvailable(RuntimeError):
    """Raised when a provider's backing model/service is unavailable
    (e.g. transformers weights not cached in a zero-egress environment)."""
