"""Shared HTTP inference-provider plumbing for the module ecosystem.

Reference: the bulk of ``modules/`` (text2vec-openai, generative-cohere, …)
are thin HTTP clients around hosted or self-hosted inference APIs, built on
shared client plumbing in ``usecases/modulecomponents`` (batch vectorizer,
rate limits, key propagation). This module is the equivalent surface,
table-driven instead of one package per provider:

- a ``Transport`` callable (url, headers, payload) -> parsed JSON, so tests
  inject a fake and zero-egress deployments fail with ``ModuleNotAvailable``
  instead of a socket error buried in a request thread;
- request/response *styles* (openai, cohere, ollama, google, …) shared by
  the many providers that clone each other's wire format;
- ``APIVectorizer`` / ``APIReranker`` / ``APIGenerative`` /
  ``APIMultiModal`` / ``APIMultiVector`` capability classes parameterized
  by a ``ProviderSpec`` row (see ``providers.py`` for the catalog).

API keys come from the spec's env var (reference reads the same names, e.g.
``OPENAI_APIKEY``) or an ``api_key`` entry in ``init()`` config; endpoints
can be overridden per deployment (reference baseURL class setting).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import numpy as np

from weaviate_tpu.modules.base import (
    Generative,
    ModuleNotAvailable,
    MultiModalVectorizer,
    MultiVectorVectorizer,
    Reranker,
    Vectorizer,
)

Transport = Callable[[str, dict, dict], dict]


def urllib_transport(url: str, headers: dict, payload: dict,
                     timeout: float = 30.0) -> dict:
    """Default transport. In a zero-egress deployment every call lands in
    ``ModuleNotAvailable`` with the provider URL, which API handlers map to
    a clean 422 instead of a 500."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **headers})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise ModuleNotAvailable(f"inference API unreachable: {url}: {e}")


@dataclass
class ProviderSpec:
    """One provider row: module name + wire format + defaults."""

    name: str                 # module name, e.g. "text2vec-openai"
    style: str                # wire format key in STYLES
    endpoint: str             # default URL ({model} substituted)
    key_env: str = ""         # env var with the API key
    auth: str = "bearer"      # bearer | x-api-key | header:<Name> | none
    model: str = ""           # default model
    dims: int = 0             # embedding dims of the default model
    extra: dict = field(default_factory=dict)  # style-specific payload knobs


class _APIBase:
    """Config resolution shared by every API-backed capability class."""

    def __init__(self, spec: ProviderSpec,
                 transport: Optional[Transport] = None):
        self.spec = spec
        self.name = spec.name
        self.transport: Transport = transport or urllib_transport
        self._cfg: dict = {}

    def init(self, config: Optional[dict] = None) -> None:
        self._cfg = dict(config or {})

    @property
    def model(self) -> str:
        return self._cfg.get("model", self.spec.model)

    def _endpoint(self) -> str:
        base = (self._cfg.get("baseURL")
                or os.environ.get(self.spec.name.upper().replace("-", "_")
                                  + "_ENDPOINT")
                or self.spec.endpoint)
        return base.replace("{model}", self.model)

    def _headers(self) -> dict:
        key = (self._cfg.get("api_key")
               or (os.environ.get(self.spec.key_env, "")
                   if self.spec.key_env else ""))
        if not key:
            if self.spec.auth == "none":
                return {}
            raise ModuleNotAvailable(
                f"{self.name}: no API key (set {self.spec.key_env or 'api_key'})")
        if self.spec.auth == "bearer":
            return {"Authorization": f"Bearer {key}"}
        if self.spec.auth == "x-api-key":
            return {"x-api-key": key, "anthropic-version": "2023-06-01"} \
                if "anthropic" in self.name else {"x-api-key": key}
        if self.spec.auth.startswith("header:"):
            return {self.spec.auth.split(":", 1)[1]: key}
        return {}

    def _call(self, payload: dict) -> dict:  # graftlint: reply-raises
        return self.transport(self._endpoint(), self._headers(), payload)


# ---------------------------------------------------------------------------
# wire styles: build embed / generate / rerank payloads and parse replies
# ---------------------------------------------------------------------------

def _f32(rows) -> np.ndarray:
    return np.asarray(rows, np.float32)


def _openai_embed(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    out = p._call({"input": list(texts), "model": p.model, **p.spec.extra})
    data = sorted(out["data"], key=lambda d: d.get("index", 0))
    return _f32([d["embedding"] for d in data])


def _cohere_embed(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    out = p._call({"texts": list(texts), "model": p.model,
                   "input_type": p.spec.extra.get(
                       "input_type", "search_document")})
    emb = out["embeddings"]
    return _f32(emb["float"] if isinstance(emb, dict) else emb)


def _hf_embed(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    vecs = p._call({"inputs": list(texts),
                    "options": {"wait_for_model": True}})
    a = np.asarray(vecs, np.float32)
    # token-level outputs mean-pool to sentence vectors
    return a.mean(axis=1) if a.ndim == 3 else a


def _ollama_embed(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    out = p._call({"model": p.model, "input": list(texts)})
    return _f32(out["embeddings"])


def _google_embed(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    out = p._call({"instances": [{"content": t} for t in texts]})
    return _f32([pr["embeddings"]["values"] for pr in out["predictions"]])


def _bedrock_embed(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    # reference signs SigV4 via the AWS SDK; here the endpoint must be a
    # pre-authed proxy/gateway (key still forwarded as bearer)
    rows = [p._call({"inputText": t})["embedding"] for t in texts]
    return _f32(rows)


def _local_vectorize(p: _APIBase, texts: Sequence[str]) -> np.ndarray:
    # self-hosted inference container contract (reference
    # text2vec-transformers/multi2vec-clip sidecars): POST /vectorize
    rows = [p._call({"text": t})["vector"] for t in texts]
    return _f32(rows)


EMBED_STYLES: dict[str, Callable[[_APIBase, Sequence[str]], np.ndarray]] = {
    "openai": _openai_embed,
    "cohere": _cohere_embed,
    "huggingface": _hf_embed,
    "ollama": _ollama_embed,
    "google": _google_embed,
    "bedrock": _bedrock_embed,
    "local": _local_vectorize,
}


def _openai_chat(p: _APIBase, prompt: str) -> str:
    out = p._call({"model": p.model, "messages": [
        {"role": "user", "content": prompt}], **p.spec.extra})
    return out["choices"][0]["message"]["content"]


def _anthropic_chat(p: _APIBase, prompt: str) -> str:
    out = p._call({"model": p.model, "max_tokens": 1024,
                   "messages": [{"role": "user", "content": prompt}]})
    return "".join(b.get("text", "") for b in out["content"])


def _cohere_chat(p: _APIBase, prompt: str) -> str:
    return p._call({"model": p.model, "message": prompt})["text"]


def _ollama_generate(p: _APIBase, prompt: str) -> str:
    return p._call({"model": p.model, "prompt": prompt,
                    "stream": False})["response"]


def _google_generate(p: _APIBase, prompt: str) -> str:
    out = p._call({"contents": [{"parts": [{"text": prompt}]}]})
    return out["candidates"][0]["content"]["parts"][0]["text"]


def _bedrock_generate(p: _APIBase, prompt: str) -> str:
    return p._call({"prompt": prompt})["completion"]


GENERATE_STYLES: dict[str, Callable[[_APIBase, str], str]] = {
    "openai": _openai_chat,
    "anthropic": _anthropic_chat,
    "cohere": _cohere_chat,
    "ollama": _ollama_generate,
    "google": _google_generate,
    "bedrock": _bedrock_generate,
}


def _cohere_rerank(p: _APIBase, query: str,
                   docs: Sequence[str]) -> list[float]:
    # cohere/voyage/jina share this shape; nvidia's variant returns
    # "rankings" rows scored by "logit"
    out = p._call({"model": p.model, "query": query,
                   "documents": list(docs)})
    rows = out.get("results") or out.get("data") or out.get("rankings") or []
    scores = [0.0] * len(docs)
    for r in rows:
        scores[int(r["index"])] = float(
            r.get("relevance_score", r.get("logit", 0.0)))
    return scores


RERANK_STYLES = {"cohere": _cohere_rerank}


# ---------------------------------------------------------------------------
# capability classes
# ---------------------------------------------------------------------------

class APIVectorizer(_APIBase, Vectorizer):
    def __init__(self, spec: ProviderSpec,
                 transport: Optional[Transport] = None):
        super().__init__(spec, transport)
        self.dims = spec.dims

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        return EMBED_STYLES[self.spec.style](self, texts)

    def vectorize_query(self, text: str) -> np.ndarray:
        if self.spec.style == "cohere":
            out = self._call({"texts": [text], "model": self.model,
                              "input_type": "search_query"})
            emb = out["embeddings"]
            return _f32(emb["float"] if isinstance(emb, dict) else emb)[0]
        return self.vectorize([text])[0]


class APIGenerative(_APIBase, Generative):
    def generate(self, prompt: str, context_documents: Sequence[str],
                 grouped: bool = False) -> str:
        if context_documents:
            ctx = "\n".join(context_documents)
            prompt = f"{prompt}\n\nContext:\n{ctx}"
        return GENERATE_STYLES[self.spec.style](self, prompt)


class APIReranker(_APIBase, Reranker):
    def rerank(self, query: str, documents: Sequence[str]) -> list[float]:
        return RERANK_STYLES[self.spec.style](self, query, documents)


class APIMultiModal(_APIBase, MultiModalVectorizer):
    """Image+text providers. Text goes through the spec's embed style;
    images through the provider's image field convention."""

    def __init__(self, spec: ProviderSpec,
                 transport: Optional[Transport] = None):
        super().__init__(spec, transport)
        self.dims = spec.dims

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        if self.spec.style == "local":
            return _local_vectorize(self, texts)
        return EMBED_STYLES[self.spec.style](self, texts)

    def vectorize_image(self, images_b64: Sequence[str]) -> np.ndarray:
        if self.spec.style == "local":
            rows = [self._call({"image": b})["vector"] for b in images_b64]
            return _f32(rows)
        if self.spec.style == "bedrock":
            # titan image embedding takes one image per request
            rows = [self._call({"inputImage": b})["embedding"]
                    for b in images_b64]
            return _f32(rows)
        if self.spec.style == "cohere":
            out = self._call({"model": self.model, "input_type": "image",
                              "images": list(images_b64)})
            emb = out["embeddings"]
            return _f32(emb["float"] if isinstance(emb, dict) else emb)
        if self.spec.style == "google":
            out = self._call({"instances": [
                {"image": {"bytesBase64Encoded": b}} for b in images_b64]})
            return _f32([pr["imageEmbedding"] for pr in out["predictions"]])
        # openai-shaped multimodal (jina/nvidia/voyage): typed input rows
        out = self._call({"model": self.model, "input": [
            {"image": b} for b in images_b64]})
        data = sorted(out["data"], key=lambda d: d.get("index", 0))
        return _f32([d["embedding"] for d in data])


class APIMultiVector(_APIBase, MultiVectorVectorizer):
    """ColBERT-style providers (jina v2 multivector API shape)."""

    def __init__(self, spec: ProviderSpec,
                 transport: Optional[Transport] = None):
        super().__init__(spec, transport)
        self.dims = spec.dims

    def vectorize_multi(self, texts: Sequence[str]) -> list[np.ndarray]:
        out = self._call({"model": self.model, "input": list(texts),
                          **self.spec.extra})
        data = sorted(out["data"], key=lambda d: d.get("index", 0))
        return [_f32(d["embeddings"]) for d in data]
