"""Offline text vectorizers: the reference's local/self-contained embedders.

Reference counterparts:
- ``modules/text2vec-contextionary`` — the classic c11y: per-word vectors
  composed (idf-weighted centroid) into a document vector, with stopword
  removal and compound-word splitting.
- ``modules/text2vec-bigram`` — experimental character-bigram embedder.
- ``modules/text2vec-morph`` — morphology-aware variant (stems share mass).
- ``modules/text2vec-model2vec`` — static token-embedding table, mean-pooled.

All four here are deterministic and dependency-free: per-token vectors come
from a seeded hash (a stand-in for trained tables — swap the token-vector
function for real weights without touching composition), so the composition
semantics (weighting, stopwords, pooling) match the reference while staying
runnable in a zero-egress image.
"""

from __future__ import annotations

import hashlib
import math
from typing import Sequence

import numpy as np

from weaviate_tpu.inverted.analyzer import STOPWORDS_EN, tokenize
from weaviate_tpu.modules.base import Vectorizer


def _token_vec(token: str, dims: int, seed: str) -> np.ndarray:
    """Deterministic dense unit vector per token (trained-table stand-in)."""
    h = hashlib.blake2b(f"{seed}:{token}".encode(), digest_size=32).digest()
    rng = np.random.default_rng(int.from_bytes(h[:8], "big"))
    v = rng.standard_normal(dims).astype(np.float32)
    return v / (np.linalg.norm(v) + 1e-12)


def _split_compound(tok: str, vocab_check) -> list[str]:
    """Greedy 2-way compound split ("bathtub" -> bath+tub) when both halves
    look like words — the c11y does this against its vocabulary."""
    if len(tok) < 6:
        return [tok]
    for cut in range(3, len(tok) - 2):
        a, b = tok[:cut], tok[cut:]
        if vocab_check(a) and vocab_check(b):
            return [a, b]
    return [tok]


class ContextionaryVectorizer(Vectorizer):
    """Compositional word-centroid embedder (reference
    ``text2vec-contextionary`` Vectorizer.Corpi → centroid)."""

    name = "text2vec-contextionary"

    def __init__(self, dims: int = 300):
        self.dims = dims
        self._df: dict[str, int] = {}  # corpus-side doc freq for idf weights
        self._docs = 0

    def _idf(self, tok: str) -> float:
        df = self._df.get(tok, 0)
        return 1.0 + math.log((self._docs + 1) / (df + 1))

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dims), np.float32)
        common = STOPWORDS_EN
        for i, text in enumerate(texts):
            toks = [t for t in tokenize(text, "word") if t not in common]
            expanded: list[str] = []
            for t in toks:
                expanded.extend(_split_compound(t, lambda w: len(w) >= 3))
            self._docs += 1
            for t in set(expanded):
                self._df[t] = self._df.get(t, 0) + 1
            if not expanded:
                continue
            acc = np.zeros(self.dims, np.float32)
            for t in expanded:
                acc += self._idf(t) * _token_vec(t, self.dims, "c11y")
            n = float(np.linalg.norm(acc))
            out[i] = acc / n if n > 0 else acc
        return out


class BigramVectorizer(Vectorizer):
    """Character-bigram embedder (reference ``text2vec-bigram``)."""

    name = "text2vec-bigram"

    def __init__(self, dims: int = 256):
        self.dims = dims

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dims), np.float32)
        for i, text in enumerate(texts):
            s = " " + " ".join(tokenize(text, "lowercase")) + " "
            for j in range(len(s) - 1):
                bg = s[j:j + 2]
                h = int.from_bytes(
                    hashlib.blake2b(bg.encode(), digest_size=8).digest(),
                    "big")
                out[i, h % self.dims] += (1.0 if (h >> 63) & 1 else -1.0)
            n = float(np.linalg.norm(out[i]))
            if n > 0:
                out[i] /= n
        return out


def _stem(tok: str) -> str:
    """Tiny suffix-stripping stemmer (Porter-lite) so inflected forms share
    a base vector, which is the point of the morph module."""
    for suf in ("ingly", "edly", "ing", "edly", "ed", "ies", "es", "s",
                "ly", "er", "est"):
        if tok.endswith(suf) and len(tok) - len(suf) >= 3:
            return tok[: len(tok) - len(suf)]
    return tok


class MorphVectorizer(Vectorizer):
    """Morphology-aware embedder (reference ``text2vec-morph``): each token
    contributes its stem vector plus a damped surface-form vector."""

    name = "text2vec-morph"

    def __init__(self, dims: int = 256):
        self.dims = dims

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dims), np.float32)
        for i, text in enumerate(texts):
            toks = tokenize(text, "word")
            if not toks:
                continue
            acc = np.zeros(self.dims, np.float32)
            for t in toks:
                acc += _token_vec(_stem(t), self.dims, "morph")
                acc += 0.25 * _token_vec(t, self.dims, "morph-surface")
            n = float(np.linalg.norm(acc))
            out[i] = acc / n if n > 0 else acc
        return out


class Model2VecVectorizer(Vectorizer):
    """Static-table mean-pooled embedder (reference ``text2vec-model2vec``:
    distilled static token embeddings, no attention at inference)."""

    name = "text2vec-model2vec"

    def __init__(self, dims: int = 256):
        self.dims = dims
        self._cache: dict[str, np.ndarray] = {}

    def _lookup(self, tok: str) -> np.ndarray:
        v = self._cache.get(tok)
        if v is None:
            v = _token_vec(tok, self.dims, "m2v")
            if len(self._cache) < 200_000:
                self._cache[tok] = v
        return v

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dims), np.float32)
        for i, text in enumerate(texts):
            toks = tokenize(text, "word")
            if not toks:
                continue
            acc = np.add.reduce([self._lookup(t) for t in toks])
            n = float(np.linalg.norm(acc))
            out[i] = acc / n if n > 0 else acc
        return out
