"""Auxiliary NLP modules: QnA, summarization, NER, spellcheck, dummies.

Reference counterparts: ``modules/qna-transformers`` + ``qna-openai``
(extractive/abstractive answers for the GraphQL ``ask`` argument),
``sum-transformers`` (``_additional { summary }``), ``ner-transformers``
(``_additional { tokens }``), ``text-spellcheck`` (nearText autocorrect),
and the ``*-dummy`` providers the reference ships for CI.

The transformers-backed modules load a cached HF pipeline when available and
otherwise fall back to an honest classical algorithm (extractive answer
matching, frequency-based extractive summary, capitalized-span NER) — the
``meta()`` payload reports which backend answered so operators can tell.
"""

from __future__ import annotations

import logging
import math
import re
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.inverted.analyzer import STOPWORDS_EN, tokenize
from weaviate_tpu.modules.base import (
    Generative,
    MultiModalVectorizer,
    NERTagger,
    QnA,
    Reranker,
    SpellChecker,
    Summarizer,
)


def _try_pipeline(task: str, model: str):
    """HF pipeline if its weights are in the local cache; None otherwise
    (zero-egress: never attempt a download — offline env vars make the miss
    immediate instead of N retried HEAD requests)."""
    import os

    os.environ.setdefault("HF_HUB_OFFLINE", "1")
    os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
    try:
        from transformers import pipeline

        return pipeline(task, model=model, local_files_only=True)
    except Exception:
        # third-party loader can raise anything; absence of the model is
        # an expected, logged degradation to the heuristic path
        logging.getLogger("weaviate_tpu.modules").debug(
            "transformers pipeline %s/%s unavailable", task, model,
            exc_info=True)
        return None


def _sentences(text: str) -> list[str]:
    return [s.strip() for s in re.split(r"(?<=[.!?])\s+", text) if s.strip()]


class TransformersQnA(QnA):
    """Extractive QA (reference ``qna-transformers``). Fallback: the
    sentence sharing the most question terms, span = the sentence."""

    name = "qna-transformers"

    def __init__(self, model: str = "distilbert-base-cased-distilled-squad"):
        self._model_name = model
        self._pipe = None
        self._probed = False

    def _backend(self):
        if not self._probed:
            self._pipe = _try_pipeline("question-answering", self._model_name)
            self._probed = True
        return self._pipe

    def meta(self) -> dict:
        # no _backend() here: meta() is called by /v1/meta for every module
        # and must not trigger the transformers import/probe
        m = super().meta()
        m["backend"] = ("transformers" if self._pipe is not None
                        else ("lexical" if self._probed else "lazy"))
        return m

    def answer(self, question: str, context: str) -> dict:
        pipe = self._backend()
        if pipe is not None:
            r = pipe(question=question, context=context)
            return {"answer": r["answer"], "certainty": float(r["score"]),
                    "start": int(r["start"]), "end": int(r["end"])}
        q_toks = set(tokenize(question, "word")) - STOPWORDS_EN
        best, best_score = None, 0.0
        for sent in _sentences(context):
            toks = set(tokenize(sent, "word"))
            overlap = len(q_toks & toks) / max(len(q_toks), 1)
            if overlap > best_score:
                best, best_score = sent, overlap
        if best is None or best_score == 0.0:
            return {"answer": None, "certainty": 0.0, "start": -1, "end": -1}
        start = context.find(best)
        return {"answer": best, "certainty": round(best_score, 4),
                "start": start, "end": start + len(best)}


class OpenAIQnA(QnA):
    """Abstractive QA via a generative provider (reference ``qna-openai``
    prompts the completions API with question + context)."""

    name = "qna-openai"

    def __init__(self, generative: Optional[Generative] = None):
        self._gen = generative

    def init(self, config: Optional[dict] = None) -> None:
        if self._gen is not None:
            self._gen.init(config)

    def answer(self, question: str, context: str) -> dict:
        if self._gen is None:
            from weaviate_tpu.modules.base import ModuleNotAvailable

            raise ModuleNotAvailable("qna-openai: no generative backend")
        text = self._gen.generate(
            f"Answer strictly from the context.\n\nContext:\n{context}\n\n"
            f"Question: {question}\nAnswer:", [])
        return {"answer": text.strip(), "certainty": 0.0,
                "start": -1, "end": -1}


class TransformersSummarizer(Summarizer):
    """Reference ``sum-transformers``. Fallback: frequency-scored extractive
    summary (top sentences by non-stopword term frequency, original order)."""

    name = "sum-transformers"

    def __init__(self, model: str = "sshleifer/distilbart-cnn-12-6",
                 max_sentences: int = 3):
        self._model_name = model
        self.max_sentences = max_sentences
        self._pipe = None
        self._probed = False

    def _backend(self):
        if not self._probed:
            self._pipe = _try_pipeline("summarization", self._model_name)
            self._probed = True
        return self._pipe

    def meta(self) -> dict:
        m = super().meta()
        m["backend"] = ("transformers" if self._pipe is not None
                        else ("extractive" if self._probed else "lazy"))
        return m

    def summarize(self, text: str) -> str:
        pipe = self._backend()
        if pipe is not None:
            return pipe(text, truncation=True)[0]["summary_text"]
        sents = _sentences(text)
        if len(sents) <= self.max_sentences:
            return text
        freq: dict[str, int] = {}
        for s in sents:
            for t in tokenize(s, "word"):
                if t not in STOPWORDS_EN:
                    freq[t] = freq.get(t, 0) + 1
        def score(s: str) -> float:
            toks = [t for t in tokenize(s, "word") if t not in STOPWORDS_EN]
            return sum(freq[t] for t in toks) / math.sqrt(len(toks)) \
                if toks else 0.0
        ranked = sorted(range(len(sents)), key=lambda i: -score(sents[i]))
        keep = sorted(ranked[: self.max_sentences])
        return " ".join(sents[i] for i in keep)


class TransformersNER(NERTagger):
    """Reference ``ner-transformers``. Fallback: capitalized multi-word
    spans tagged MISC (mid-sentence capitalization heuristic)."""

    name = "ner-transformers"

    def __init__(self, model: str = "dslim/bert-base-NER"):
        self._model_name = model
        self._pipe = None
        self._probed = False

    def _backend(self):
        if not self._probed:
            self._pipe = _try_pipeline("token-classification", self._model_name)
            self._probed = True
        return self._pipe

    def meta(self) -> dict:
        m = super().meta()
        m["backend"] = ("transformers" if self._pipe is not None
                        else ("heuristic" if self._probed else "lazy"))
        return m

    def tag(self, text: str) -> list[dict]:
        pipe = self._backend()
        if pipe is not None:
            out = pipe(text, aggregation_strategy="simple")
            return [{"entity": r["entity_group"], "word": r["word"],
                     "start": int(r["start"]), "end": int(r["end"]),
                     "certainty": float(r["score"])} for r in out]
        ents = []
        for m in re.finditer(
                r"(?<![.!?]\s)(?<!^)\b([A-Z][a-z]+(?:\s+[A-Z][a-z]+)*)\b",
                text):
            ents.append({"entity": "MISC", "word": m.group(1),
                         "start": m.start(1), "end": m.end(1),
                         "certainty": 0.5})
        return ents


# a compact common-word core; check() also learns from configured vocab
_BASE_WORDS = (
    "the of and a to in is was he for it with as his on be at by had not "
    "are but from or have an they which one you were all her she there "
    "would their we him been has when who will no more if out so said what "
    "up its about than into them can only other time new some could these "
    "two may first then do any like my now over such our man me even most "
    "made after also did many off before must well back through years much "
    "where your way down should because each just those people how too "
    "good very world search query vector database index engine data text "
    "document result filter schema object class property tenant backup"
).split()


class SpellCheck(SpellChecker):
    """Reference ``text-spellcheck``: corrects query text before
    vectorization. Local symspell-style edit-distance-1 lookup against a
    frequency dictionary (base vocabulary + words learned via init config
    ``vocabulary`` or ``learn()``)."""

    name = "text-spellcheck"

    def __init__(self):
        self._freq: dict[str, int] = {w: 100 for w in _BASE_WORDS}

    def init(self, config: Optional[dict] = None) -> None:
        for w in (config or {}).get("vocabulary", []):
            self.learn(w)

    def learn(self, word: str, count: int = 1) -> None:
        w = word.lower()
        self._freq[w] = self._freq.get(w, 0) + count

    def _edits1(self, w: str):
        letters = "abcdefghijklmnopqrstuvwxyz"
        splits = [(w[:i], w[i:]) for i in range(len(w) + 1)]
        for a, b in splits:
            if b:
                yield a + b[1:]                      # delete
                yield a + b[0] + b[0] + b[1:]        # double
            if len(b) > 1:
                yield a + b[1] + b[0] + b[2:]        # transpose
            for c in letters:
                if b:
                    yield a + c + b[1:]              # replace
                yield a + c + b                      # insert

    def _correct(self, w: str) -> str:
        if w in self._freq or len(w) < 3 or not w.isalpha():
            return w
        cands = {c for c in self._edits1(w) if c in self._freq}
        if not cands:
            return w
        return max(cands, key=lambda c: self._freq[c])

    def check(self, text: str) -> dict:
        parts = re.split(r"(\W+)", text)
        changes = []
        out = []
        for p in parts:
            c = self._correct(p.lower()) if p.isalpha() else p
            if p.isalpha() and c != p.lower():
                changes.append({"original": p, "corrected": c})
                out.append(c)
            else:
                out.append(p)
        return {"original": text, "corrected": "".join(out),
                "changes": changes}


class TransformersReranker(Reranker):
    """Cross-encoder reranker (reference ``modules/reranker-transformers``:
    a self-hosted cross-encoder service). Uses a cached HF text-
    classification pipeline when present; otherwise falls back to the
    lexical BM25-ish scorer so reranking stays functional offline."""

    name = "reranker-transformers"

    def __init__(self, model: str = "cross-encoder/ms-marco-MiniLM-L-6-v2"):
        self._model_name = model
        self._pipe = None
        self._probed = False

    def _backend(self):
        if not self._probed:
            self._pipe = _try_pipeline("text-classification", self._model_name)
            self._probed = True
        return self._pipe

    def meta(self) -> dict:
        m = super().meta()
        m["backend"] = ("transformers" if self._pipe is not None
                        else ("lexical" if self._probed else "lazy"))
        return m

    def rerank(self, query: str, documents: Sequence[str]) -> list[float]:
        pipe = self._backend()
        if pipe is not None:
            out = pipe([{"text": query, "text_pair": d} for d in documents],
                       truncation=True)
            return [float(r["score"]) for r in out]
        from weaviate_tpu.modules.reranker_lexical import LexicalReranker

        return LexicalReranker().rerank(query, documents)


# ---------------------------------------------------------------------------
# dummy providers (reference generative-dummy / multi2vec-dummy /
# reranker-dummy: deterministic no-network CI modules)
# ---------------------------------------------------------------------------

class DummyGenerative(Generative):
    name = "generative-dummy"

    def generate(self, prompt: str, context_documents: Sequence[str],
                 grouped: bool = False) -> str:
        n = len(context_documents)
        return f"[dummy] prompt={prompt!r} docs={n}"


class DummyReranker(Reranker):
    name = "reranker-dummy"

    def rerank(self, query: str, documents: Sequence[str]) -> list[float]:
        # reverse input order, deterministically
        n = len(documents)
        return [float(n - i) for i in range(n)]


class DummyMultiModal(MultiModalVectorizer):
    name = "multi2vec-dummy"
    dims = 64

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        from weaviate_tpu.modules.text2vec_hash import HashVectorizer

        return HashVectorizer(dims=self.dims).vectorize(texts)

    def vectorize_image(self, images_b64: Sequence[str]) -> np.ndarray:
        import hashlib

        out = np.zeros((len(images_b64), self.dims), np.float32)
        for i, b in enumerate(images_b64):
            h = hashlib.blake2b(b.encode(), digest_size=32).digest()
            rng = np.random.default_rng(int.from_bytes(h[:8], "big"))
            v = rng.standard_normal(self.dims).astype(np.float32)
            out[i] = v / (np.linalg.norm(v) + 1e-12)
        return out
