"""reranker-lexical: offline token-overlap reranker.

Plays the role of the reference's ``modules/reranker-transformers`` /
``reranker-dummy`` in a zero-egress environment: scores each document by
smoothed query-token overlap (per-token idf-free BM25-ish saturation).
"""

from __future__ import annotations

from collections import Counter
from typing import Sequence

from weaviate_tpu.inverted.analyzer import tokenize
from weaviate_tpu.modules.base import Reranker


class LexicalReranker(Reranker):
    name = "reranker-lexical"

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = k1
        self.b = b

    def rerank(self, query: str, documents: Sequence[str]) -> list[float]:
        q_tokens = set(tokenize(query, "word"))
        if not q_tokens:
            return [0.0] * len(documents)
        doc_tokens = [Counter(tokenize(d, "word")) for d in documents]
        avg_len = max(
            1.0, sum(sum(c.values()) for c in doc_tokens) / max(1, len(documents))
        )
        scores = []
        for c in doc_tokens:
            dl = sum(c.values())
            s = 0.0
            for t in q_tokens:
                tf = c.get(t, 0)
                if tf:
                    denom = tf + self.k1 * (1 - self.b + self.b * dl / avg_len)
                    s += tf * (self.k1 + 1) / denom
            scores.append(s)
        return scores
