"""text2vec-hash: deterministic feature-hashing embedder (offline-safe).

The stand-in for the reference's sidecar vectorizers
(``modules/text2vec-contextionary``): token feature hashing with positional
n-grams into a fixed-dim space, L2-normalized. Deterministic, dependency-free,
and batched — the TPU path treats embeddings as data, so any real provider
can replace this without touching the write/query integration.
"""

from __future__ import annotations

import hashlib
import math
from typing import Sequence

import numpy as np

from weaviate_tpu.inverted.analyzer import tokenize
from weaviate_tpu.modules.base import Vectorizer


def _bucket(token: str, seed: int, dims: int) -> tuple[int, float]:
    h = hashlib.blake2b(f"{seed}:{token}".encode(), digest_size=8).digest()
    v = int.from_bytes(h, "big")
    idx = v % dims
    sign = 1.0 if (v >> 63) & 1 else -1.0
    return idx, sign


class HashVectorizer(Vectorizer):
    name = "text2vec-hash"

    def __init__(self, dims: int = 256, ngrams: int = 2):
        self.dims = dims
        self.ngrams = ngrams

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dims), np.float32)
        for i, text in enumerate(texts):
            toks = tokenize(text, "word")
            feats = list(toks)
            for n in range(2, self.ngrams + 1):
                feats.extend(
                    "_".join(toks[j:j + n]) for j in range(len(toks) - n + 1)
                )
            for tok in feats:
                # idf-ish damping: shorter tokens are commoner, weigh less
                w = 1.0 + math.log1p(len(tok))
                idx, sign = _bucket(tok, 0, self.dims)
                out[i, idx] += sign * w
            norm = float(np.linalg.norm(out[i]))
            if norm > 0:
                out[i] /= norm
        return out
