"""ColBERT-style MaxSim (late interaction) as a device rerank module.

The same Chamfer similarity ``index/multivector.py:maxsim_scores``
computes host-side — sum over query tokens of the max dot product over
document tokens — expressed over a BATCHED candidate axis so it slots
into the fused search program's rerank stage (reference
``hnsw/search.go:927`` rescore loop → one einsum per batch).
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar

import numpy as np

from weaviate_tpu.modules.device.base import DeviceRerankModule


def batched_maxsim(q_tokens, q_mask, cand_tokens, cand_mask):
    """[B, C] masked MaxSim, jit-traceable — THE late-interaction core
    every device module composes (the finite-guard semantics live here
    once): masked doc tokens are -inf before the max; a candidate with
    no live tokens contributes 0 per query token (matching the host
    ``maxsim_scores`` guard); masked query tokens contribute 0."""
    import jax.numpy as jnp

    sims = jnp.einsum("bqd,bctd->bcqt", q_tokens, cand_tokens,
                      preferred_element_type=jnp.float32)
    sims = jnp.where(cand_mask[:, :, None, :], sims, -jnp.inf)
    best = jnp.max(sims, axis=3)                     # [B, C, Tq]
    best = jnp.where(jnp.isfinite(best), best, 0.0)
    best = jnp.where(q_mask[:, None, :], best, 0.0)
    return jnp.sum(best, axis=2)                     # [B, C]


def batched_maxsim_host(q_tokens, q_mask, cand_tokens, cand_mask
                        ) -> np.ndarray:
    """The numpy twin of :func:`batched_maxsim` (fallback tier)."""
    sims = np.einsum("bqd,bctd->bcqt",
                     np.asarray(q_tokens, np.float32),
                     np.asarray(cand_tokens, np.float32))
    sims = np.where(cand_mask[:, :, None, :], sims, -np.inf)
    best = sims.max(axis=3)
    best = np.where(np.isfinite(best), best, 0.0)
    best = np.where(q_mask[:, None, :], best, 0.0)
    return best.sum(axis=2).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class MaxSimRerank(DeviceRerankModule):
    """score[b, c] = Σ_q max_t  q_tokens[b, q] · cand_tokens[b, c, t]."""

    name: ClassVar[str] = "rerank-maxsim"

    def score(self, q_tokens, q_mask, cand_tokens, cand_mask):
        return batched_maxsim(q_tokens, q_mask, cand_tokens, cand_mask)

    def host_score(self, q_tokens, q_mask, cand_tokens, cand_mask
                   ) -> np.ndarray:
        return batched_maxsim_host(q_tokens, q_mask, cand_tokens,
                                   cand_mask)
