"""Device module tier: rerank hooks fused into the one-dispatch search.

See ``docs/modules.md`` for the taxonomy (host vs device tiers), the
DeviceRerankModule contract, fallback semantics, and HBM rent.
"""

from weaviate_tpu.modules.device.base import (
    DeviceRerankModule,
    RerankRequest,
    build_device_reranker,
    device_reranker_catalog,
)
from weaviate_tpu.modules.device.linear import LinearRerank
from weaviate_tpu.modules.device.maxsim import MaxSimRerank
from weaviate_tpu.modules.device.store import CandidateTokenStore

__all__ = [
    "DeviceRerankModule",
    "RerankRequest",
    "build_device_reranker",
    "device_reranker_catalog",
    "MaxSimRerank",
    "LinearRerank",
    "CandidateTokenStore",
]
