"""Cross-encoder-shaped linear rerank module.

A real cross-encoder jointly attends over (query, document); its
device-fusable approximation here is a weighted blend of the two
interaction features the token planes support — late interaction
(MaxSim) and mean-pooled dot product — with frozen scalar weights. The
point of shipping it is the SHAPE: it proves the module tier accepts a
second, differently-parameterized scorer behind the same hook (the
weights are dataclass fields, so two differently-weighted instances are
distinct jit identities and never share a coalesced batch).
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar

import numpy as np

from weaviate_tpu.modules.device.base import DeviceRerankModule


@dataclasses.dataclass(frozen=True)
class LinearRerank(DeviceRerankModule):
    """score = w_max·MaxSim + w_mean·(mean_q · mean_d) + bias."""

    name: ClassVar[str] = "rerank-linear"

    w_max: float = 1.0
    w_mean: float = 0.25
    bias: float = 0.0

    def score(self, q_tokens, q_mask, cand_tokens, cand_mask):
        import jax.numpy as jnp

        from weaviate_tpu.modules.device.maxsim import batched_maxsim

        maxsim = batched_maxsim(q_tokens, q_mask, cand_tokens, cand_mask)

        qn = jnp.maximum(jnp.sum(q_mask, axis=1), 1)[:, None]
        qm = (jnp.sum(
            jnp.where(q_mask[..., None], q_tokens, 0.0), axis=1)
            / qn.astype(jnp.float32))                               # [B, D]
        cn = jnp.maximum(jnp.sum(cand_mask, axis=2), 1)[..., None]
        cm = (jnp.sum(
            jnp.where(cand_mask[..., None], cand_tokens, 0.0), axis=2)
            / cn.astype(jnp.float32))                               # [B, C, D]
        mean_dot = jnp.einsum("bd,bcd->bc", qm, cm,
                              preferred_element_type=jnp.float32)
        return (jnp.float32(self.w_max) * maxsim
                + jnp.float32(self.w_mean) * mean_dot
                + jnp.float32(self.bias))

    def host_score(self, q_tokens, q_mask, cand_tokens, cand_mask
                   ) -> np.ndarray:
        from weaviate_tpu.modules.device.maxsim import batched_maxsim_host

        q_tokens = np.asarray(q_tokens, np.float32)
        cand_tokens = np.asarray(cand_tokens, np.float32)
        maxsim = batched_maxsim_host(q_tokens, q_mask, cand_tokens,
                                     cand_mask)

        qn = np.maximum(q_mask.sum(axis=1), 1)[:, None]
        qm = np.where(q_mask[..., None], q_tokens, 0.0).sum(axis=1) / qn
        cn = np.maximum(cand_mask.sum(axis=2), 1)[..., None]
        cm = np.where(cand_mask[..., None], cand_tokens, 0.0).sum(axis=2) / cn
        mean_dot = np.einsum("bd,bcd->bc", qm, cm)
        return (self.w_max * maxsim + self.w_mean * mean_dot
                + self.bias).astype(np.float32)
