"""Device module SPI: rerank hooks that ride the fused dispatch.

The host module tier (``modules/base.py``) scores documents with Python
after search returns — fine for an external cross-encoder API, but a
host round-trip per query for math the accelerator does in microseconds.
A *device* rerank module is the TPU-native tier: a frozen (and therefore
hashable — it keys the jit cache, exactly like ``ops/device_beam.py``'s
``Scorer`` dataclasses) dataclass whose ``score`` hook is jit-traceable
and runs INSIDE the fused search program: beam → rescore → gather
candidate token planes → module score → on-device top-k, one dispatch
per batch (``docs/modules.md``).

Contract for a ``DeviceRerankModule`` implementation:

- ``@dataclasses.dataclass(frozen=True)`` with hashable fields only
  (floats/ints/strs/tuples) — the instance is a jit static argument.
- ``name``: catalog id (``rerank-*``), a plain class attribute.
- ``score(q_tokens, q_mask, cand_tokens, cand_mask) -> [B, C]`` —
  jit-traceable, HIGHER is better. Shapes: ``q_tokens [B, Tq, D]``,
  ``q_mask [B, Tq]`` bool, ``cand_tokens [B, C, T, D]``,
  ``cand_mask [B, C, T]`` bool. The hook must never sync to host
  (``np.asarray``/``.item()``/callbacks) — graftlint's
  ``module-hook-host-sync`` rule enforces this.
- ``host_score(...)`` — the same math in numpy, used by the host
  fallback tier (warm-tier tenants, latched beams, flat-triage paths)
  and as the reference ordering in tests. NOT part of the traced
  region; numpy is expected here.
"""

from __future__ import annotations

from typing import ClassVar, Optional

import numpy as np

from weaviate_tpu.modules.base import Module


class DeviceRerankModule:
    """Protocol base (isinstance marker) for device rerank scorers."""

    name: ClassVar[str] = "rerank-device"

    def score(self, q_tokens, q_mask, cand_tokens, cand_mask):
        raise NotImplementedError

    def host_score(self, q_tokens, q_mask, cand_tokens, cand_mask
                   ) -> np.ndarray:
        raise NotImplementedError

    # modules scored inside jit call the instance like a function — keep
    # the two spellings one implementation
    def __call__(self, q_tokens, q_mask, cand_tokens, cand_mask):
        return self.score(q_tokens, q_mask, cand_tokens, cand_mask)


class DeviceRerankerProvider(Module):
    """Registry-visible wrapper: the reference registers every module in
    one Provider catalog (``usecases/modules/modules.go``), so device
    rerankers appear there too — discoverable via ``registry.list()``
    and type-checked via ``registry.device_reranker(name)``. ``build``
    mints the frozen scorer instance the fused stage jits against."""

    device_rerank = True  # capability marker (modules.base.module_type)

    def __init__(self, cls: type):
        self.name = cls.name
        self._cls = cls

    def module_type(self) -> str:
        return "device-rerank"

    def build(self, **params) -> DeviceRerankModule:
        return self._cls(**params)


def device_reranker_catalog() -> dict[str, type]:
    """name -> frozen module class for every in-tree device reranker."""
    from weaviate_tpu.modules.device.linear import LinearRerank
    from weaviate_tpu.modules.device.maxsim import MaxSimRerank

    return {
        MaxSimRerank.name: MaxSimRerank,
        LinearRerank.name: LinearRerank,
    }


def build_device_reranker(name: str, params: Optional[dict] = None
                          ) -> DeviceRerankModule:
    """Instantiate a frozen device reranker from the catalog. Unknown
    params raise (a typo'd weight silently defaulting would change
    ranking quality without a trace)."""
    catalog = device_reranker_catalog()
    cls = catalog.get(name)
    if cls is None:
        raise KeyError(
            f"device rerank module {name!r} not in catalog "
            f"{sorted(catalog)}")
    return cls(**(params or {}))


class RerankRequest:
    """Per-request fused-rerank spec carried into the coalescing
    dispatcher. Its identity joins the batch-group key: two requests may
    share one device batch only when their module instance AND padded
    query-token shape agree — a differently-reranked request must never
    ride a batch whose program scores with someone else's module.

    ``query_tokens=None`` is *self* mode: each query row's own vector is
    its (single-element) token set — the natural form for reranking a
    plain nearVector search. A ``[Tq, D]`` matrix is an explicit
    late-interaction token set shared by every row of this request
    (typically B=1). Tq pads to a pow2 bucket so steady traffic shares a
    handful of compiles instead of one per distinct token count.
    """

    __slots__ = ("module", "query_tokens", "query_mask", "tq_pad")

    def __init__(self, module: DeviceRerankModule,
                 query_tokens: Optional[np.ndarray] = None):
        self.module = module
        if query_tokens is None:
            self.query_tokens = None
            self.query_mask = None
            self.tq_pad = 1
            return
        qt = np.atleast_2d(np.asarray(query_tokens, np.float32))
        tq = qt.shape[0]
        self.tq_pad = 1 << max(0, (tq - 1).bit_length())
        padded = np.zeros((self.tq_pad, qt.shape[1]), np.float32)
        padded[:tq] = qt
        mask = np.zeros((self.tq_pad,), bool)
        mask[:tq] = True
        self.query_tokens = padded
        self.query_mask = mask

    @property
    def group_key(self) -> tuple:
        """Dispatcher batch-group identity (hashable)."""
        dims = (None if self.query_tokens is None
                else self.query_tokens.shape[1])
        return (self.module, self.tq_pad, dims)

    def batch_for(self, queries: np.ndarray
                  ) -> tuple[DeviceRerankModule, np.ndarray, np.ndarray]:
        """→ (module, q_tokens [B, Tq, D], q_mask [B, Tq]) for one
        request's query rows (the dispatcher concatenates these across a
        coalesced group)."""
        q = np.atleast_2d(np.asarray(queries, np.float32))
        b = q.shape[0]
        if self.query_tokens is None:
            return (self.module, q[:, None, :].astype(np.float32),
                    np.ones((b, 1), bool))
        qt = np.broadcast_to(
            self.query_tokens[None], (b, *self.query_tokens.shape))
        qm = np.broadcast_to(self.query_mask[None], (b, self.tq_pad))
        return self.module, np.ascontiguousarray(qt), \
            np.ascontiguousarray(qm)
