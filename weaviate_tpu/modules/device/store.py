"""Candidate token planes: the HBM residency of the device rerank tier.

Fused rerank gathers each candidate's token set INSIDE the search
program, so the token sets must live in HBM as doc-id-addressed planes:
``tokens [cap, T, D]`` + ``mask [cap, T]``. This store keeps the host
copy authoritative (writes land there first; the device mirror scatters
dirty rows before a search, exactly like ``ops/device_beam.py``'s
``DeviceAdjacency``), which also makes the host fallback tier and
tiering demotion free: dropping the device planes loses nothing.

Mesh mode row-shards the planes along the same shard axis as every
other HBM plane (``capacity`` tracks the backend's
``device_plane_capacity`` via ``cap_fn`` so the beam's local candidate
ids index the local token block directly).

Tiering: the planes pay HBM rent like code planes do — ``nbytes`` feeds
the index's ledger total, ``drop_device``/``sync`` are the
demote/promote legs (``TieredResidency`` semantics: demotion releases
HBM, the next hot search re-uploads wholesale at identical shapes so
compiled rerank programs keep hitting their cache).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


def _pow2(n: int) -> int:
    return 1 << max(0, (max(1, n) - 1).bit_length())


class CandidateTokenStore:
    def __init__(self, dims: int, max_tokens: int = 8,
                 cap_fn: Optional[Callable[[], int]] = None,
                 mesh=None, initial_capacity: int = 1024):
        self.dims = dims
        self.tmax = _pow2(max_tokens)
        self.cap_fn = cap_fn
        self.mesh = mesh
        cap = self._target_capacity(initial_capacity)
        self._tokens = np.zeros((cap, self.tmax, dims), np.float32)
        self._mask = np.zeros((cap, self.tmax), bool)
        self._dev: Optional[tuple] = None
        self._dev_shape: Optional[tuple] = None
        self._dirty: set[int] = set()

    # -- host-authoritative writes ---------------------------------------
    def _target_capacity(self, need: int) -> int:
        cap = max(1024, need)
        if self.cap_fn is not None:
            # align to the backend's device plane so ids (and, on a
            # mesh, LOCAL block offsets) index both the same way
            cap = max(cap, int(self.cap_fn()))
        if self.mesh is not None:
            from weaviate_tpu.parallel.mesh import mesh_size

            n = mesh_size(self.mesh)
            cap = ((cap + n - 1) // n) * n
        return cap

    def _ensure(self, need_rows: int, need_tokens: int) -> None:
        cap = self._target_capacity(need_rows)
        tmax = self.tmax if need_tokens <= self.tmax else _pow2(need_tokens)
        if cap <= self._tokens.shape[0] and tmax == self.tmax:
            return
        cap = max(cap, self._tokens.shape[0])
        grown_t = np.zeros((cap, tmax, self.dims), np.float32)
        grown_m = np.zeros((cap, tmax), bool)
        old = self._tokens.shape[0]
        grown_t[:old, : self.tmax] = self._tokens
        grown_m[:old, : self.tmax] = self._mask
        self._tokens, self._mask, self.tmax = grown_t, grown_m, tmax
        # shape moved: the mirror re-uploads wholesale on the next sync
        self._dev = None
        self._dirty.clear()

    def put(self, doc_ids: np.ndarray, token_sets) -> None:
        doc_ids = np.asarray(doc_ids, np.int64).reshape(-1)
        if len(doc_ids) == 0:
            return
        if isinstance(token_sets, np.ndarray) and token_sets.ndim == 3:
            # uniform [m, T, D] block (bulk loads): one vectorized write
            t = token_sets.astype(np.float32, copy=False)
            self._ensure(int(doc_ids.max()) + 1, t.shape[1])
            self._tokens[doc_ids, : t.shape[1]] = t
            self._tokens[doc_ids, t.shape[1]:] = 0.0
            self._mask[doc_ids, : t.shape[1]] = True
            self._mask[doc_ids, t.shape[1]:] = False
            self._dirty.update(int(d) for d in doc_ids)
        else:
            sets = [np.atleast_2d(np.asarray(t, np.float32))
                    for t in token_sets]
            self._ensure(int(doc_ids.max()) + 1,
                         max(s.shape[0] for s in sets))
            for d, t in zip(doc_ids, sets):
                d = int(d)
                n = t.shape[0]
                self._tokens[d, :n] = t
                self._tokens[d, n:] = 0.0
                self._mask[d, :n] = True
                self._mask[d, n:] = False
                self._dirty.add(d)
        if len(self._dirty) > self._tokens.shape[0] // 2:
            # more dirty rows than a scatter is worth: next sync
            # re-uploads wholesale instead of building a huge index list
            self._dev = None
            self._dirty.clear()

    def delete(self, doc_ids: np.ndarray) -> None:
        cap = self._tokens.shape[0]
        for d in np.asarray(doc_ids, np.int64).reshape(-1):
            d = int(d)
            if d < cap:
                self._mask[d] = False
                self._dirty.add(d)

    # -- reads ------------------------------------------------------------
    def host_planes(self) -> tuple[np.ndarray, np.ndarray]:
        """(tokens, mask) host arrays — the fallback tier's scoring
        source and the mirror's upload source."""
        return self._tokens, self._mask

    def sync(self, min_rows: int = 0):
        """→ (tokens, mask) device arrays, up to date. Wholesale upload
        on shape change / first hot touch after a demotion; dirty-row
        scatter otherwise (mesh scatters stay sharded via the pinned
        out-sharding the plane was placed with). ``min_rows``: the
        caller's candidate-id space (e.g. the adjacency mirror's row
        count) — the plane must cover it or a clipped gather would read
        the wrong row's tokens."""
        import jax
        import jax.numpy as jnp

        # the backend plane may have grown since the last write — track
        # it so beam candidate ids never index past the token plane
        self._ensure(max(1, min_rows), self.tmax)
        shape = self._tokens.shape
        if self._dev is None or self._dev_shape != shape:
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from weaviate_tpu.parallel.mesh import SHARD_AXIS

                self._dev = (
                    jax.device_put(self._tokens, NamedSharding(
                        self.mesh, P(SHARD_AXIS, None, None))),
                    jax.device_put(self._mask, NamedSharding(
                        self.mesh, P(SHARD_AXIS, None))),
                )
            else:
                self._dev = (jnp.asarray(self._tokens),
                             jnp.asarray(self._mask))
            self._dev_shape = shape
            self._dirty.clear()
            return self._dev
        if self._dirty:
            # atomic swap: writers keep adding ids concurrently (same
            # contract as DeviceAdjacency.sync)
            dirty, self._dirty = self._dirty, set()
            idx = np.fromiter(
                (i for i in dirty if i < shape[0]), np.int32)
            if len(idx):
                toks, mask = self._dev
                jidx = jnp.asarray(idx)
                toks = toks.at[jidx].set(jnp.asarray(self._tokens[idx]))
                mask = mask.at[jidx].set(jnp.asarray(self._mask[idx]))
                self._dev = (toks, mask)
        return self._dev

    # -- tiered residency -------------------------------------------------
    @property
    def device_resident(self) -> bool:
        return self._dev is not None

    @property
    def nbytes(self) -> int:
        """HBM rent of the mirrored planes (0 while demoted)."""
        if self._dev is None:
            return 0
        return sum(a.nbytes for a in self._dev)

    @property
    def host_bytes(self) -> int:
        return self._tokens.nbytes + self._mask.nbytes

    def drop_device(self) -> int:
        """Release the planes from HBM (warm demotion); the host copy is
        authoritative, so nothing is lost. Returns bytes released."""
        freed = self.nbytes
        self._dev = None
        self._dev_shape = None
        self._dirty.clear()
        return freed

    # -- checkpoint -------------------------------------------------------
    def save(self, path: str) -> None:
        """Persist the host planes as an atomic sidecar next to the
        owning index's checkpoint — a restored index must rerank against
        the SAME token sets it checkpointed, never empty masks."""
        import os

        tmp = path + ".rrtok.tmp.npz"
        np.savez_compressed(tmp, tokens=self._tokens, mask=self._mask)
        os.replace(tmp, path + ".rrtok.npz")

    def load(self, path: str) -> bool:
        """Restore the host planes from the sidecar; False when absent
        or corrupt (the caller treats the whole checkpoint as missing —
        half a checkpoint is no checkpoint)."""
        import os

        p = path + ".rrtok.npz"
        if not os.path.exists(p):
            return False
        try:
            with np.load(p) as z:
                tokens = z["tokens"]
                mask = z["mask"]
        except (OSError, ValueError, KeyError):
            return False
        if tokens.ndim != 3 or tokens.shape[2] != self.dims \
                or mask.shape != tokens.shape[:2]:
            return False
        self._tokens = tokens.astype(np.float32, copy=False)
        self._mask = mask.astype(bool, copy=False)
        self.tmax = tokens.shape[1]
        self._dev = None
        self._dev_shape = None
        self._dirty.clear()
        return True
