"""generative-template: offline RAG answer synthesis.

Mirrors the reference's ``test/generative-dummy`` module shape: fills the
user's prompt with retrieved context so the generate() additional-property
pipeline (``usecases/modules`` → explorer "generate") is exercised end-to-end
without an external LLM. ``{property}`` placeholders interpolate document
text, like the reference's singlePrompt templating.
"""

from __future__ import annotations

from typing import Sequence

from weaviate_tpu.modules.base import Generative


class TemplateGenerative(Generative):
    name = "generative-template"

    def generate(
        self,
        prompt: str,
        context_documents: Sequence[str],
        grouped: bool = False,
    ) -> str:
        ctx = "\n".join(f"- {d}" for d in context_documents)
        if grouped:
            return f"{prompt}\n[context]\n{ctx}"
        # single-prompt mode: one doc expected
        doc = context_documents[0] if context_documents else ""
        return prompt.replace("{text}", doc) if "{text}" in prompt else (
            f"{prompt}\n[context]\n{doc}"
        )

    def generate_single(self, prompt_template: str, properties: dict) -> str:
        """singlePrompt: ``{prop}`` placeholders filled from object props."""
        out = prompt_template
        for k, v in properties.items():
            out = out.replace("{" + k + "}", str(v))
        return out
