"""ref2vec-centroid: object vector = centroid of referenced objects' vectors.

Reference: ``modules/ref2vec-centroid`` — recomputes an object's vector as
the mean (the only calculation method the reference ships) of the vectors of
the objects it references. The write path calls ``centroid`` with the
resolved referenced vectors.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.modules.base import Module


class Ref2VecCentroid(Module):
    name = "ref2vec-centroid"

    def centroid(self, vectors: Sequence[np.ndarray]) -> Optional[np.ndarray]:
        vecs = [np.asarray(v, np.float32) for v in vectors if v is not None]
        if not vecs:
            return None
        return np.mean(np.stack(vecs), axis=0)
