"""Module registry: name → provider instance, capability-checked accessors.

Reference: ``usecases/modules/modules.go:45`` (Provider) — registered at
startup (``configure_api.go registerModules``), consulted by the write path
(vectorize on import), query path (nearText), and additional-property
providers (rerank/generate).
"""

from __future__ import annotations

from typing import Optional

from weaviate_tpu.modules.base import (
    Generative,
    Module,
    Reranker,
    Vectorizer,
)


class ModuleRegistry:
    def __init__(self):
        self._modules: dict[str, Module] = {}

    def register(self, module: Module) -> None:
        self._modules[module.name] = module

    def get(self, name: str) -> Module:
        m = self._modules.get(name)
        if m is None:
            raise KeyError(f"module {name!r} not registered")
        return m

    def has(self, name: str) -> bool:
        return name in self._modules

    def vectorizer(self, name: str) -> Vectorizer:
        m = self.get(name)
        if not isinstance(m, Vectorizer):
            raise TypeError(f"module {name!r} is not a vectorizer")
        return m

    def reranker(self, name: str) -> Reranker:
        m = self.get(name)
        if not isinstance(m, Reranker):
            raise TypeError(f"module {name!r} is not a reranker")
        return m

    def generative(self, name: str) -> Generative:
        m = self.get(name)
        if not isinstance(m, Generative):
            raise TypeError(f"module {name!r} is not generative")
        return m

    def list(self) -> dict[str, dict]:
        return {name: m.meta() for name, m in self._modules.items()}


def default_registry() -> ModuleRegistry:
    """The baked-in providers (reference: registerModules defaults)."""
    from weaviate_tpu.modules.generative_template import TemplateGenerative
    from weaviate_tpu.modules.ref2vec_centroid import Ref2VecCentroid
    from weaviate_tpu.modules.reranker_lexical import LexicalReranker
    from weaviate_tpu.modules.text2vec_hash import HashVectorizer

    reg = ModuleRegistry()
    reg.register(HashVectorizer())
    reg.register(LexicalReranker())
    reg.register(TemplateGenerative())
    reg.register(Ref2VecCentroid())
    # transformers registers lazily: the model loads on first vectorize()
    # call and raises ModuleNotAvailable there when weights aren't cached
    # (eager probing would load ~90MB into every DB instance at startup)
    from weaviate_tpu.modules.text2vec_transformers import (
        TransformersVectorizer,
    )

    reg.register(TransformersVectorizer())
    return reg
