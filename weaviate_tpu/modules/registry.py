"""Module registry: name → provider instance, capability-checked accessors.

Reference: ``usecases/modules/modules.go:45`` (Provider) — registered at
startup (``configure_api.go registerModules``), consulted by the write path
(vectorize on import), query path (nearText), and additional-property
providers (rerank/generate).
"""

from __future__ import annotations

from typing import Optional

from weaviate_tpu.modules.base import (
    Generative,
    Module,
    MultiModalVectorizer,
    MultiVectorVectorizer,
    NERTagger,
    QnA,
    Reranker,
    SpellChecker,
    Summarizer,
    Vectorizer,
)


class ModuleRegistry:
    def __init__(self):
        self._modules: dict[str, Module] = {}

    def register(self, module: Module) -> None:
        self._modules[module.name] = module

    def get(self, name: str) -> Module:
        m = self._modules.get(name)
        if m is None:
            raise KeyError(f"module {name!r} not registered")
        return m

    def has(self, name: str) -> bool:
        return name in self._modules

    def _typed(self, name: str, cls: type, what: str):
        m = self.get(name)
        if not isinstance(m, cls):
            raise TypeError(f"module {name!r} is not {what}")
        return m

    def vectorizer(self, name: str) -> Vectorizer:
        return self._typed(name, Vectorizer, "a vectorizer")

    def reranker(self, name: str) -> Reranker:
        return self._typed(name, Reranker, "a reranker")

    def generative(self, name: str) -> Generative:
        return self._typed(name, Generative, "generative")

    def multimodal(self, name: str) -> MultiModalVectorizer:
        return self._typed(name, MultiModalVectorizer, "multi-modal")

    def multivector(self, name: str) -> MultiVectorVectorizer:
        return self._typed(name, MultiVectorVectorizer,
                           "a multivector provider")

    def qna(self, name: str) -> QnA:
        return self._typed(name, QnA, "a QnA provider")

    def summarizer(self, name: str) -> Summarizer:
        return self._typed(name, Summarizer, "a summarizer")

    def ner(self, name: str) -> NERTagger:
        return self._typed(name, NERTagger, "a NER tagger")

    def spellchecker(self, name: str) -> SpellChecker:
        return self._typed(name, SpellChecker, "a spellchecker")

    def device_reranker(self, name: str):
        """A device rerank provider (``modules/device/``) — checked via
        the capability marker, not isinstance, so this module keeps its
        zero-import view of the device tier."""
        m = self.get(name)
        if not getattr(m, "device_rerank", False):
            raise TypeError(f"module {name!r} is not a device reranker")
        return m

    def has_device_reranker(self, name: str) -> bool:
        return self.has(name) and getattr(
            self.get(name), "device_rerank", False)

    def list(self) -> dict[str, dict]:
        return {name: m.meta() for name, m in self._modules.items()}


def default_registry() -> ModuleRegistry:
    """The full provider catalog (reference: registerModules wires all 67
    enabled modules; here every provider registers and the unreachable ones
    fail per-call with ``ModuleNotAvailable``)."""
    from weaviate_tpu.modules.extras import (
        DummyGenerative,
        DummyMultiModal,
        DummyReranker,
        OpenAIQnA,
        SpellCheck,
        TransformersNER,
        TransformersQnA,
        TransformersReranker,
        TransformersSummarizer,
    )
    from weaviate_tpu.modules.generative_template import TemplateGenerative
    from weaviate_tpu.modules.local_text import (
        BigramVectorizer,
        ContextionaryVectorizer,
        Model2VecVectorizer,
        MorphVectorizer,
    )
    from weaviate_tpu.modules.providers import register_api_providers
    from weaviate_tpu.modules.ref2vec_centroid import Ref2VecCentroid
    from weaviate_tpu.modules.reranker_lexical import LexicalReranker
    from weaviate_tpu.modules.text2vec_hash import HashVectorizer

    reg = ModuleRegistry()
    reg.register(HashVectorizer())
    reg.register(LexicalReranker())
    reg.register(TemplateGenerative())
    reg.register(Ref2VecCentroid())
    # transformers registers lazily: the model loads on first vectorize()
    # call and raises ModuleNotAvailable there when weights aren't cached
    # (eager probing would load ~90MB into every DB instance at startup)
    from weaviate_tpu.modules.text2vec_transformers import (
        TransformersVectorizer,
    )

    reg.register(TransformersVectorizer())
    # offline local embedders
    reg.register(ContextionaryVectorizer())
    reg.register(BigramVectorizer())
    reg.register(MorphVectorizer())
    reg.register(Model2VecVectorizer())
    # auxiliary NLP + CI dummies
    reg.register(TransformersQnA())
    reg.register(TransformersSummarizer())
    reg.register(TransformersNER())
    reg.register(TransformersReranker())
    reg.register(SpellCheck())
    reg.register(DummyGenerative())
    reg.register(DummyReranker())
    reg.register(DummyMultiModal())
    # device rerank tier (modules/device/): fused into the one-dispatch
    # search pipeline; the registry entry is the discovery/config surface
    from weaviate_tpu.modules.device.base import (
        DeviceRerankerProvider,
        device_reranker_catalog,
    )

    for cls in device_reranker_catalog().values():
        reg.register(DeviceRerankerProvider(cls))
    # the hosted/self-hosted API catalog (gated per call in zero-egress)
    register_api_providers(reg)
    # qna-openai rides the generative-openai client
    reg.register(OpenAIQnA(reg.generative("generative-openai")))
    return reg
