"""Pluggable model-provider modules (reference L7: ``modules/`` + the SPI in
``entities/modulecapabilities`` and registry in ``usecases/modules``)."""

from weaviate_tpu.modules.base import (
    Generative,
    Module,
    ModuleNotAvailable,
    Reranker,
    Vectorizer,
)
from weaviate_tpu.modules.registry import ModuleRegistry, default_registry

__all__ = [
    "Module", "Vectorizer", "Reranker", "Generative", "ModuleNotAvailable",
    "ModuleRegistry", "default_registry",
]
