"""Server entry point: REST + gRPC on one DB, env-var configured.

Reference: ``cmd/weaviate-server/main.go`` + the composition root
``adapters/handlers/rest/configure_api.go`` (env-driven config from
``usecases/config/environment.go``). Run as:

    python -m weaviate_tpu.server

Env vars (reference names where they exist):
  PERSISTENCE_DATA_PATH   data directory (default ./weaviate-tpu-data)
  DEFAULT_HTTP_PORT       REST port (default 8080)
  GRPC_PORT               gRPC port (default 50051; empty string disables)
  AUTHENTICATION_APIKEY_ENABLED        "true" to require API keys
  AUTHENTICATION_APIKEY_ALLOWED_KEYS   comma-separated keys
  AUTHENTICATION_APIKEY_USERS          comma-separated user names (parallel)
  AUTHENTICATION_ANONYMOUS_ACCESS_ENABLED  default "true"
  AUTHORIZATION_RBAC_ENABLED           "true" to enforce RBAC
  AUTHORIZATION_RBAC_ROOT_USERS        comma-separated always-admin users
"""

from __future__ import annotations

import os
import signal
import sys
import threading


def config_from_env() -> dict:
    keys = [k for k in os.environ.get(
        "AUTHENTICATION_APIKEY_ALLOWED_KEYS", "").split(",") if k]
    users = [u for u in os.environ.get(
        "AUTHENTICATION_APIKEY_USERS", "").split(",") if u]
    api_keys = dict(zip(keys, users + ["user"] * (len(keys) - len(users))))
    return {
        "data_path": os.environ.get(
            "PERSISTENCE_DATA_PATH", "./weaviate-tpu-data"),
        "http_port": int(os.environ.get("DEFAULT_HTTP_PORT", "8080")),
        "grpc_port": os.environ.get("GRPC_PORT", "50051"),
        "api_keys": api_keys
        if os.environ.get("AUTHENTICATION_APIKEY_ENABLED") == "true" else {},
        "anonymous": os.environ.get(
            "AUTHENTICATION_ANONYMOUS_ACCESS_ENABLED", "true") != "false",
        "rbac_enabled": os.environ.get(
            "AUTHORIZATION_RBAC_ENABLED") == "true",
        "rbac_root_users": [
            u for u in os.environ.get(
                "AUTHORIZATION_RBAC_ROOT_USERS", "").split(",") if u],
        # OIDC (reference AUTHENTICATION_OIDC_*): zero-egress deployments
        # configure keys inline instead of discovery
        "oidc_enabled": os.environ.get(
            "AUTHENTICATION_OIDC_ENABLED") == "true",
        "oidc_issuer": os.environ.get("AUTHENTICATION_OIDC_ISSUER", ""),
        "oidc_client_id": os.environ.get("AUTHENTICATION_OIDC_CLIENT_ID", ""),
        "oidc_username_claim": os.environ.get(
            "AUTHENTICATION_OIDC_USERNAME_CLAIM", "sub"),
        "oidc_groups_claim": os.environ.get(
            "AUTHENTICATION_OIDC_GROUPS_CLAIM", "groups"),
        "oidc_jwks_file": os.environ.get("AUTHENTICATION_OIDC_JWKS_FILE", ""),
        "oidc_hs256_secret": os.environ.get(
            "AUTHENTICATION_OIDC_HS256_SECRET", ""),
    }


def main() -> int:
    from weaviate_tpu.api.grpc_server import GrpcAPI
    from weaviate_tpu.api.rest import AuthConfig, RestAPI
    from weaviate_tpu.core.db import DB

    cfg = config_from_env()
    # persistent compilation cache BEFORE anything can jit (DB open may
    # compile during checkpoint replay): restarted nodes deserialize
    # yesterday's executables instead of re-paying XLA (ROADMAP item 3,
    # docs/compile_cache.md). Default base under the data path; env /
    # runtime knob / kill switch override inside configure().
    from weaviate_tpu.utils import compile_cache

    compile_cache.configure(
        compile_cache.resolve_base_dir()
        or os.path.join(cfg["data_path"], "compile_cache"))
    db = DB(cfg["data_path"])
    oidc = None
    if cfg["oidc_enabled"]:
        import json as _json

        from weaviate_tpu.auth.oidc import OIDCConfig

        jwks = None
        if cfg["oidc_jwks_file"]:
            with open(cfg["oidc_jwks_file"]) as f:
                jwks = _json.load(f)
        oidc = OIDCConfig(
            issuer=cfg["oidc_issuer"], client_id=cfg["oidc_client_id"],
            jwks=jwks,
            hs256_secret=(cfg["oidc_hs256_secret"].encode()
                          if cfg["oidc_hs256_secret"] else None),
            username_claim=cfg["oidc_username_claim"],
            groups_claim=cfg["oidc_groups_claim"],
        )
    auth = AuthConfig(api_keys=cfg["api_keys"],
                      anonymous_access=cfg["anonymous"], oidc=oidc)
    rbac = None
    if cfg["rbac_enabled"]:
        from weaviate_tpu.auth.rbac import RBACController

        rbac = RBACController(path=f"{cfg['data_path']}/rbac.json",
                              root_users=cfg["rbac_root_users"])
    # runtime-overrides hot reload + usage telemetry (reference
    # config/runtime + usecases/telemetry)
    from weaviate_tpu.monitoring.telemetry import Telemeter
    from weaviate_tpu.utils.runtime_config import RUNTIME

    RUNTIME.start()
    telemeter = Telemeter(db)
    telemeter.start()

    # boot prewarm: compile the shape-bucket lattice of every open
    # collection in the background; /v1/.well-known/ready reports
    # ``warming: true`` until it drains so orchestrators can gate
    # traffic on compile-free first queries
    from weaviate_tpu.utils import prewarm

    prewarm.prewarm_db(db, reason="boot", block=False)

    rest = RestAPI(db, auth=auth, rbac=rbac)
    rest.telemeter = telemeter
    rest_srv = rest.serve(host="0.0.0.0", port=cfg["http_port"],
                          background=True)
    print(f"REST listening on :{rest_srv.server_port}", file=sys.stderr)

    grpc_api = None
    if cfg["grpc_port"]:
        grpc_api = GrpcAPI(db, auth=auth, rbac=rbac)
        port = grpc_api.serve(host="0.0.0.0", port=int(cfg["grpc_port"]))
        print(f"gRPC listening on :{port}", file=sys.stderr)

    stop = threading.Event()

    def _sig(*_):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    stop.wait()

    print("shutting down", file=sys.stderr)
    rest.shutdown()
    if grpc_api is not None:
        grpc_api.shutdown()
    telemeter.stop()
    RUNTIME.stop()
    db.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
