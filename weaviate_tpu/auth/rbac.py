"""RBAC authorization: roles, permissions, user assignments.

Reference: ``usecases/auth/authorization/`` (casbin-backed controller with
roles/permissions over collections/tenants/backups/roles resources,
raft-replicated in ``cluster/rbac``). Policies here are explicit
action+resource-pattern pairs evaluated with fnmatch — the same
verb/resource model without the casbin dependency — persisted to a JSON
file (the raft FSM slot when clustered).
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Optional

# the reference's authorization verbs (authorization/authorization.go)
ACTIONS = (
    "read_schema", "create_schema", "update_schema", "delete_schema",
    "read_data", "create_data", "update_data", "delete_data",
    "read_tenants", "update_tenants",
    "manage_backups", "read_cluster", "manage_cluster", "read_nodes",
    "manage_roles", "read_roles",
    # dynamic db-user management (reference authorization/users domain)
    "read_users", "create_users", "update_users", "delete_users",
)


class Forbidden(PermissionError):
    def __init__(self, user, action, resource):
        super().__init__(
            f"user {user!r} is not allowed to {action} on {resource!r}")


@dataclass
class Permission:
    action: str
    resource: str = "*"  # e.g. "collections/*", "collections/Article"

    def matches(self, action: str, resource: str) -> bool:
        return (self.action == action
                and fnmatch.fnmatchcase(resource, self.resource))


@dataclass
class Role:
    name: str
    permissions: list[Permission] = field(default_factory=list)

    def allows(self, action: str, resource: str) -> bool:
        return any(p.matches(action, resource) for p in self.permissions)


def builtin_roles() -> dict[str, Role]:
    """Reference built-ins: admin (everything), viewer (read-only)."""
    return {
        "admin": Role("admin", [Permission(a, "*") for a in ACTIONS]),
        "viewer": Role("viewer", [
            Permission(a, "*") for a in ACTIONS if a.startswith("read_")
        ]),
    }


class RBACController:
    def __init__(self, path: Optional[str] = None,
                 root_users: Optional[list[str]] = None):
        self._lock = threading.RLock()
        self.path = path
        self.roles: dict[str, Role] = builtin_roles()
        self.assignments: dict[str, set[str]] = {}
        # AUTHORIZATION_RBAC_ROOT_USERS: always admin, can't be locked out
        self.root_users = set(root_users or [])
        self._load()

    # -- persistence -------------------------------------------------------
    def _load(self):
        if not self.path or not os.path.exists(self.path):
            return
        with open(self.path) as f:
            d = json.load(f)
        for rd in d.get("roles", []):
            self.roles[rd["name"]] = Role(
                rd["name"],
                [Permission(**p) for p in rd.get("permissions", [])],
            )
        self.assignments = {
            u: set(rs) for u, rs in d.get("assignments", {}).items()
        }

    def _persist(self):
        if not self.path:
            return
        d = {
            "roles": [
                {"name": r.name,
                 "permissions": [
                     {"action": p.action, "resource": p.resource}
                     for p in r.permissions
                 ]}
                for r in self.roles.values()
                if r.name not in ("admin", "viewer")
            ],
            "assignments": {u: sorted(rs)
                            for u, rs in self.assignments.items()},
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(d, f, indent=1)
        os.replace(tmp, self.path)

    # -- role management ---------------------------------------------------
    def upsert_role(self, name: str,
                    permissions: list[dict | Permission]) -> Role:
        perms = []
        for p in permissions:
            if isinstance(p, Permission):
                perms.append(p)
            else:
                perms.append(Permission(p["action"], p.get("resource", "*")))
        for p in perms:
            if p.action not in ACTIONS:
                raise ValueError(f"unknown action {p.action!r}")
        with self._lock:
            if name in ("admin", "viewer"):
                raise ValueError(f"built-in role {name!r} is immutable")
            role = Role(name, perms)
            self.roles[name] = role
            self._persist()
            return role

    def delete_role(self, name: str) -> None:
        with self._lock:
            if name in ("admin", "viewer"):
                raise ValueError(f"built-in role {name!r} is immutable")
            self.roles.pop(name, None)
            for rs in self.assignments.values():
                rs.discard(name)
            self._persist()

    def assign(self, user: str, role: str) -> None:
        with self._lock:
            if role not in self.roles:
                raise KeyError(f"role {role!r} not found")
            self.assignments.setdefault(user, set()).add(role)
            self._persist()

    def revoke(self, user: str, role: str) -> None:
        with self._lock:
            self.assignments.get(user, set()).discard(role)
            self._persist()

    def add_permissions(self, name: str,
                        permissions: list[dict]) -> Role:
        """Append permissions to an existing role (reference
        /authz/roles/{id}/add-permissions)."""
        with self._lock:
            if name in ("admin", "viewer"):
                raise ValueError(f"built-in role {name!r} is immutable")
            role = self.roles.get(name)
            if role is None:
                raise KeyError(f"role {name!r} not found")
            # validate EVERY entry before appending ANY: a bad later
            # entry must not leave earlier grants live-but-unpersisted
            parsed = []
            for p in permissions:
                if "action" not in p:
                    raise ValueError("permission missing 'action'")
                perm = Permission(p["action"], p.get("resource", "*"))
                if perm.action not in ACTIONS:
                    raise ValueError(f"unknown action {perm.action!r}")
                parsed.append(perm)
            have = {(p.action, p.resource) for p in role.permissions}
            for perm in parsed:
                if (perm.action, perm.resource) not in have:
                    role.permissions.append(perm)
                    have.add((perm.action, perm.resource))
            self._persist()
            return role

    def remove_permissions(self, name: str,
                           permissions: list[dict]) -> Role:
        with self._lock:
            if name in ("admin", "viewer"):
                raise ValueError(f"built-in role {name!r} is immutable")
            role = self.roles.get(name)
            if role is None:
                raise KeyError(f"role {name!r} not found")
            if any("action" not in p for p in permissions):
                raise ValueError("permission missing 'action'")
            drop = {(p["action"], p.get("resource", "*"))
                    for p in permissions}
            role.permissions = [
                p for p in role.permissions
                if (p.action, p.resource) not in drop]
            self._persist()
            return role

    def role_has_permission(self, name: str, action: str,
                            resource: str = "*") -> bool:
        with self._lock:
            role = self.roles.get(name)
            if role is None:
                raise KeyError(f"role {name!r} not found")
            return role.allows(action, resource)

    def users_with_role(self, name: str) -> list[str]:
        """Users assigned a role (reference /authz/roles/{id}/users)."""
        with self._lock:
            if name not in self.roles:
                raise KeyError(f"role {name!r} not found")
            out = sorted(u for u, rs in self.assignments.items()
                         if name in rs)
            if name == "admin":
                out = sorted(set(out) | set(self.root_users))
            return out

    def user_roles(self, user: str) -> list[str]:
        with self._lock:
            roles = set(self.assignments.get(user, set()))
            if user in self.root_users:
                roles.add("admin")
            return sorted(roles)

    # -- the check ---------------------------------------------------------
    def authorize(self, user: Optional[str], action: str,
                  resource: str = "*", groups=()) -> None:
        """Raises Forbidden unless some role of the user (or one of their
        OIDC groups, assigned as ``group:<name>`` principals — reference
        RBAC group subjects) allows it. ``user=None`` (anonymous) has no
        roles — deny everything when RBAC is on, like the reference's
        authz with anonymous access."""
        with self._lock:
            if user is not None and user in self.root_users:
                return
            names = set(self.assignments.get(user, set())) if user else set()
            for g in groups:
                names |= self.assignments.get(f"group:{g}", set())
            for rn in names:
                role = self.roles.get(rn)
                if role is not None and role.allows(action, resource):
                    return
        raise Forbidden(user, action, resource)
