"""OIDC bearer-token authentication (JWT validation).

Reference: ``usecases/auth/authentication/oidc/middleware.go`` — validates
RS256 JWTs against the issuer's JWKS (fetched via OIDC discovery) and maps
``username_claim``/``groups_claim`` into the principal. This deployment is
zero-egress, so keys are CONFIGURED rather than discovered: an inline JWKS
(RS256, via the ``cryptography`` package) and/or a shared HS256 secret.
Checks: signature, ``exp``/``nbf``, ``iss``, ``aud`` — the same claim set
the reference's go-oidc verifier enforces.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any, Optional


class OIDCError(RuntimeError):
    pass


def _b64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def _int_from_b64(data: str) -> int:
    return int.from_bytes(_b64url(data), "big")


class OIDCConfig:
    """Static-key OIDC validator.

    jwks: {"keys": [{kty, kid, n, e}, ...]} (RFC 7517 RSA keys)
    hs256_secret: shared secret for HS256 tokens (tests / internal services)
    """

    def __init__(self, issuer: str = "", client_id: str = "",
                 jwks: Optional[dict] = None,
                 hs256_secret: Optional[bytes] = None,
                 username_claim: str = "sub",
                 groups_claim: str = "groups",
                 clock_skew_s: int = 30):
        self.issuer = issuer
        self.client_id = client_id
        self.keys: dict[str, Any] = {}
        self.hs256_secret = hs256_secret
        self.username_claim = username_claim
        self.groups_claim = groups_claim
        self.clock_skew_s = clock_skew_s
        for jwk in (jwks or {}).get("keys", []):
            if jwk.get("kty") != "RSA":
                continue
            self.keys[jwk.get("kid", "")] = jwk

    # -- verification ------------------------------------------------------
    def _verify_rs256(self, signing: bytes, sig: bytes, kid: str) -> None:
        jwk = self.keys.get(kid) or (
            next(iter(self.keys.values())) if len(self.keys) == 1 else None)
        if jwk is None:
            raise OIDCError(f"no JWKS key for kid {kid!r}")
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding, rsa

        pub = rsa.RSAPublicNumbers(
            _int_from_b64(jwk["e"]), _int_from_b64(jwk["n"])
        ).public_key()
        try:
            pub.verify(sig, signing, padding.PKCS1v15(), hashes.SHA256())
        except Exception as e:
            raise OIDCError("invalid RS256 signature") from e

    def _verify_hs256(self, signing: bytes, sig: bytes) -> None:
        if not self.hs256_secret:
            raise OIDCError("HS256 token but no shared secret configured")
        want = hmac.new(self.hs256_secret, signing, hashlib.sha256).digest()
        if not hmac.compare_digest(want, sig):
            raise OIDCError("invalid HS256 signature")

    def validate(self, token: str) -> tuple[str, list[str]]:
        """Returns (principal, groups); raises OIDCError."""
        parts = token.split(".")
        if len(parts) != 3:
            raise OIDCError("not a JWT")
        try:
            header = json.loads(_b64url(parts[0]))
            claims = json.loads(_b64url(parts[1]))
            sig = _b64url(parts[2])
        except (ValueError, json.JSONDecodeError) as e:
            raise OIDCError("malformed JWT") from e
        signing = f"{parts[0]}.{parts[1]}".encode()
        alg = header.get("alg")
        if alg == "RS256":
            self._verify_rs256(signing, sig, header.get("kid", ""))
        elif alg == "HS256":
            self._verify_hs256(signing, sig)
        else:
            raise OIDCError(f"unsupported alg {alg!r}")

        now = time.time()
        exp = claims.get("exp")
        if exp is None:
            # a token that can never age out is a permanent credential —
            # reject like go-oidc does
            raise OIDCError("missing exp claim")
        if now > exp + self.clock_skew_s:
            raise OIDCError("token expired")
        nbf = claims.get("nbf")
        if nbf is not None and now < nbf - self.clock_skew_s:
            raise OIDCError("token not yet valid")
        if self.issuer and claims.get("iss") != self.issuer:
            raise OIDCError(f"wrong issuer {claims.get('iss')!r}")
        if self.client_id:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.client_id not in auds:
                raise OIDCError("audience mismatch")

        principal = claims.get(self.username_claim)
        if not principal:
            raise OIDCError(f"missing {self.username_claim!r} claim")
        groups = claims.get(self.groups_claim) or []
        if not isinstance(groups, list):
            groups = [groups]
        return str(principal), [str(g) for g in groups]


def make_hs256_token(claims: dict, secret: bytes) -> str:
    """Mint an HS256 JWT (tests + internal service-to-service auth)."""
    def enc(obj) -> str:
        raw = json.dumps(obj, separators=(",", ":")).encode()
        return base64.urlsafe_b64encode(raw).decode().rstrip("=")

    head = enc({"alg": "HS256", "typ": "JWT"})
    body = enc(claims)
    sig = hmac.new(secret, f"{head}.{body}".encode(), hashlib.sha256).digest()
    return f"{head}.{body}." + base64.urlsafe_b64encode(sig).decode().rstrip("=")
