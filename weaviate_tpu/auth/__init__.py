"""Authentication + authorization (reference ``usecases/auth``)."""

from weaviate_tpu.auth.rbac import (
    ACTIONS,
    Forbidden,
    Permission,
    RBACController,
    Role,
)

__all__ = ["RBACController", "Role", "Permission", "Forbidden", "ACTIONS"]
