"""Dynamic (db-backed) API-key users.

Reference: ``usecases/auth/authentication/apikey/`` dynamic keys +
``adapters/handlers/rest/operations/users`` (`/v1/users/db` create / list /
get / delete / rotate-key / activate / deactivate, `/v1/users/own-info`).
Static env keys identify fixed principals; dynamic users are created at
runtime, their secrets are returned ONCE and stored only as salted SHA-256
hashes, keys can be rotated, and deactivated users fail authentication
without being deleted.

Persistence is one atomically-replaced msgpack file under the DB dir (the
reference stores dynamic users in its raft-backed store; single-file-per-
node matches this repo's other node-local auth state).
"""

from __future__ import annotations

import hashlib
import logging
import os
import secrets
import threading
import time
from typing import Optional

import msgpack

_bak_warned = False

_PREFIX = "wv-tpu"


def _hash(secret: str, salt: bytes) -> bytes:
    return hashlib.sha256(salt + secret.encode()).digest()


class DynamicUserStore:
    """user_id -> {hash, salt, active, created_at}; key lookup is by the
    key's embedded user id (``<prefix>-<user_id>-<secret>``), so auth costs
    one hash, not a scan."""

    def __init__(self, path: str, reserved: Optional[set] = None):
        self.path = path
        self._lock = threading.Lock()
        self._users: dict[str, dict] = {}
        # principal names owned by static keys / root users: creating a db
        # user under one of these would mint a key that AUTHENTICATES AS
        # that principal (privilege escalation) — reject with a conflict,
        # like the reference's env-user collision check
        self.reserved = set(reserved or ())
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        try:
            self._users = msgpack.unpackb(raw, raw=False)
        except Exception as e:
            # FAIL CLOSED, loudly: silently starting with an empty user
            # set would lock out every dynamic key holder and hide the
            # corruption (advisor r3 finding). The operator restores from
            # the .bak written on every persist, or removes the file to
            # intentionally start fresh.
            raise RuntimeError(
                f"dynamic user store {self.path!r} is corrupt ({e!r}); "
                f"restore it (a .bak sits beside it) or delete it to "
                f"reset all db users") from e

    def _persist(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(self._users, use_bin_type=True))
            # fsync BEFORE the rename: the key was already returned to the
            # client, so a crash must not be able to lose the only copy of
            # its hash (advisor r3 finding)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(self.path):
            # rolling backup for the fail-closed corrupt-load path — a
            # HARDLINK, not a rename: the primary must exist at every
            # instant (a crash between a rename-away and the final
            # replace would silently present as "no user store")
            bak = f"{self.path}.bak"
            try:
                if os.path.exists(bak):
                    os.unlink(bak)
                os.link(self.path, bak)
            except OSError as e:
                global _bak_warned
                if not _bak_warned:
                    # the corrupt-load message points the operator at the
                    # .bak — if this filesystem can't produce one, say so
                    # (once), or that pointer is a dead end
                    _bak_warned = True
                    logging.getLogger("weaviate_tpu.auth").warning(
                        "user store backup %s not written (%s); corrupt-"
                        "store recovery will have no .bak", bak, e)
        os.replace(tmp, self.path)
        # fsync the DIRECTORY too: the rename itself is not durable until
        # the directory entry is journaled — without this a power loss
        # after create() returns can roll back to the pre-key users.db
        try:
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass

    @staticmethod
    def _make_key(user_id: str) -> tuple[str, str]:
        secret = secrets.token_urlsafe(24)
        return f"{_PREFIX}-{user_id}-{secret}", secret

    # -- management --------------------------------------------------------
    def create(self, user_id: str) -> str:
        """Create a user; returns the apikey (shown exactly once)."""
        if not user_id or "-" in user_id:
            raise ValueError("user id must be non-empty and free of '-'")
        if user_id in self.reserved:
            raise KeyError(
                f"user id {user_id!r} collides with a static principal")
        with self._lock:
            if user_id in self._users:
                raise KeyError(f"user {user_id!r} already exists")
            key, secret = self._make_key(user_id)
            salt = secrets.token_bytes(16)
            self._users[user_id] = {
                "hash": _hash(secret, salt), "salt": salt,
                "active": True, "created_at": int(time.time() * 1000),
            }
            self._persist()
            return key

    def rotate(self, user_id: str) -> str:
        """Invalidate the current key, return a fresh one."""
        with self._lock:
            u = self._users.get(user_id)
            if u is None:
                raise KeyError(f"user {user_id!r} not found")
            key, secret = self._make_key(user_id)
            u["salt"] = secrets.token_bytes(16)
            u["hash"] = _hash(secret, u["salt"])
            self._persist()
            return key

    def set_active(self, user_id: str, active: bool) -> None:
        with self._lock:
            u = self._users.get(user_id)
            if u is None:
                raise KeyError(f"user {user_id!r} not found")
            u["active"] = bool(active)
            self._persist()

    def delete(self, user_id: str) -> bool:
        with self._lock:
            if self._users.pop(user_id, None) is None:
                return False
            self._persist()
            return True

    def get(self, user_id: str) -> Optional[dict]:
        with self._lock:
            u = self._users.get(user_id)
            if u is None:
                return None
            return {"userId": user_id, "active": u["active"],
                    "createdAt": u["created_at"], "dbUserType": "db_user"}

    def list(self) -> list[dict]:
        with self._lock:
            return [{"userId": i, "active": u["active"],
                     "createdAt": u["created_at"], "dbUserType": "db_user"}
                    for i, u in self._users.items()]

    # -- authentication ----------------------------------------------------
    def principal_for_key(self, key: str) -> Optional[str]:
        """apikey -> user id; None when the key is not a dynamic key or is
        invalid/inactive (caller decides whether to fall through)."""
        if not key.startswith(f"{_PREFIX}-"):
            return None
        rest = key[len(_PREFIX) + 1:]
        user_id, sep, secret = rest.partition("-")
        if not sep:
            return None
        import hmac

        with self._lock:
            u = self._users.get(user_id)
            if u is None or not u["active"]:
                return None
            if not hmac.compare_digest(_hash(secret, u["salt"]), u["hash"]):
                return None
            return user_id
