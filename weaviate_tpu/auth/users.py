"""Dynamic (db-backed) API-key users.

Reference: ``usecases/auth/authentication/apikey/`` dynamic keys +
``adapters/handlers/rest/operations/users`` (`/v1/users/db` create / list /
get / delete / rotate-key / activate / deactivate, `/v1/users/own-info`).
Static env keys identify fixed principals; dynamic users are created at
runtime, their secrets are returned ONCE and stored only as salted SHA-256
hashes, keys can be rotated, and deactivated users fail authentication
without being deleted.

Persistence is one atomically-replaced msgpack file under the DB dir (the
reference stores dynamic users in its raft-backed store; single-file-per-
node matches this repo's other node-local auth state).
"""

from __future__ import annotations

import hashlib
import os
import secrets
import threading
import time
from typing import Optional

import msgpack

_PREFIX = "wv-tpu"


def _hash(secret: str, salt: bytes) -> bytes:
    return hashlib.sha256(salt + secret.encode()).digest()


class DynamicUserStore:
    """user_id -> {hash, salt, active, created_at}; key lookup is by the
    key's embedded user id (``<prefix>-<user_id>-<secret>``), so auth costs
    one hash, not a scan."""

    def __init__(self, path: str, reserved: Optional[set] = None):
        self.path = path
        self._lock = threading.Lock()
        self._users: dict[str, dict] = {}
        # principal names owned by static keys / root users: creating a db
        # user under one of these would mint a key that AUTHENTICATES AS
        # that principal (privilege escalation) — reject with a conflict,
        # like the reference's env-user collision check
        self.reserved = set(reserved or ())
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path, "rb") as f:
                self._users = msgpack.unpackb(f.read(), raw=False)
        except Exception:
            self._users = {}

    def _persist(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(self._users, use_bin_type=True))
        os.replace(tmp, self.path)

    @staticmethod
    def _make_key(user_id: str) -> tuple[str, str]:
        secret = secrets.token_urlsafe(24)
        return f"{_PREFIX}-{user_id}-{secret}", secret

    # -- management --------------------------------------------------------
    def create(self, user_id: str) -> str:
        """Create a user; returns the apikey (shown exactly once)."""
        if not user_id or "-" in user_id:
            raise ValueError("user id must be non-empty and free of '-'")
        if user_id in self.reserved:
            raise KeyError(
                f"user id {user_id!r} collides with a static principal")
        with self._lock:
            if user_id in self._users:
                raise KeyError(f"user {user_id!r} already exists")
            key, secret = self._make_key(user_id)
            salt = secrets.token_bytes(16)
            self._users[user_id] = {
                "hash": _hash(secret, salt), "salt": salt,
                "active": True, "created_at": int(time.time() * 1000),
            }
            self._persist()
            return key

    def rotate(self, user_id: str) -> str:
        """Invalidate the current key, return a fresh one."""
        with self._lock:
            u = self._users.get(user_id)
            if u is None:
                raise KeyError(f"user {user_id!r} not found")
            key, secret = self._make_key(user_id)
            u["salt"] = secrets.token_bytes(16)
            u["hash"] = _hash(secret, u["salt"])
            self._persist()
            return key

    def set_active(self, user_id: str, active: bool) -> None:
        with self._lock:
            u = self._users.get(user_id)
            if u is None:
                raise KeyError(f"user {user_id!r} not found")
            u["active"] = bool(active)
            self._persist()

    def delete(self, user_id: str) -> bool:
        with self._lock:
            if self._users.pop(user_id, None) is None:
                return False
            self._persist()
            return True

    def get(self, user_id: str) -> Optional[dict]:
        with self._lock:
            u = self._users.get(user_id)
            if u is None:
                return None
            return {"userId": user_id, "active": u["active"],
                    "createdAt": u["created_at"], "dbUserType": "db_user"}

    def list(self) -> list[dict]:
        with self._lock:
            return [{"userId": i, "active": u["active"],
                     "createdAt": u["created_at"], "dbUserType": "db_user"}
                    for i, u in self._users.items()]

    # -- authentication ----------------------------------------------------
    def principal_for_key(self, key: str) -> Optional[str]:
        """apikey -> user id; None when the key is not a dynamic key or is
        invalid/inactive (caller decides whether to fall through)."""
        if not key.startswith(f"{_PREFIX}-"):
            return None
        rest = key[len(_PREFIX) + 1:]
        user_id, sep, secret = rest.partition("-")
        if not sep:
            return None
        import hmac

        with self._lock:
            u = self._users.get(user_id)
            if u is None or not u["active"]:
                return None
            if not hmac.compare_digest(_hash(secret, u["salt"]), u["hash"]):
                return None
            return user_id
