"""Backup storage backends.

Reference: ``entities/modulecapabilities/backup.go`` SPI with
``modules/backup-{filesystem,s3,gcs,azure}`` implementations. The filesystem
backend is fully functional; object-store backends register only when their
SDKs exist in the environment (they don't in this zero-egress image, so they
surface as unavailable the way a reference deployment without the module
enabled would).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Optional

# backup ids are path components: no separators, no leading dot
_BACKUP_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")


def validate_backup_id(backup_id: str) -> str:
    if not _BACKUP_ID_RE.match(backup_id):
        raise ValueError(f"invalid backup id {backup_id!r}")
    return backup_id


def confine(base: str, path: str) -> str:
    """Resolve ``path`` and require it inside ``base`` (sep-aware)."""
    rbase = os.path.realpath(base)
    rpath = os.path.realpath(path)
    if rpath != rbase and not rpath.startswith(rbase + os.sep):
        raise ValueError(f"path escapes {base!r}: {path!r}")
    return path


class BackupBackend:
    """SPI: write/read a backup's files under a backup-id prefix."""

    name = "backend"

    def put_file(self, backup_id: str, rel_path: str, src_path: str) -> None:
        raise NotImplementedError

    def get_file(self, backup_id: str, rel_path: str, dst_path: str) -> None:
        raise NotImplementedError

    def put_meta(self, backup_id: str, data: bytes) -> None:
        raise NotImplementedError

    def get_meta(self, backup_id: str) -> Optional[bytes]:
        raise NotImplementedError

    def list_files(self, backup_id: str) -> list[str]:
        raise NotImplementedError

    def exists(self, backup_id: str) -> bool:
        return self.get_meta(backup_id) is not None


class FilesystemBackend(BackupBackend):
    """Reference ``modules/backup-filesystem``."""

    name = "filesystem"

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, backup_id: str, rel: str = "") -> str:
        validate_backup_id(backup_id)
        base = os.path.join(self.root, backup_id)
        return confine(base, os.path.join(base, rel))

    def put_file(self, backup_id: str, rel_path: str, src_path: str) -> None:
        dst = self._path(backup_id, rel_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy2(src_path, dst)

    def get_file(self, backup_id: str, rel_path: str, dst_path: str) -> None:
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        shutil.copy2(self._path(backup_id, rel_path), dst_path)

    def put_meta(self, backup_id: str, data: bytes) -> None:
        p = self._path(backup_id, "backup.json")
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def get_meta(self, backup_id: str) -> Optional[bytes]:
        p = self._path(backup_id, "backup.json")
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def list_files(self, backup_id: str) -> list[str]:
        base = self._path(backup_id)
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn == "backup.json":
                    continue
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, base))
        return sorted(out)


class ObjectStoreBackend(BackupBackend):
    """Backup over an object store (reference ``modules/backup-s3`` /
    ``backup-gcs`` / ``backup-azure`` — same SPI, keys are
    ``<backup_id>/<rel_path>``)."""

    def __init__(self, name: str, client):
        self.name = name
        self.client = client

    def _key(self, backup_id: str, rel: str = "") -> str:
        validate_backup_id(backup_id)
        # rel paths come from os.walk (trusted) on write but from the
        # manifest on read — normalize and refuse traversal either way
        rel = rel.replace(os.sep, "/")
        if rel.startswith("/") or ".." in rel.split("/"):
            raise ValueError(f"invalid backup path {rel!r}")
        return f"{backup_id}/{rel}" if rel else backup_id

    def put_file(self, backup_id: str, rel_path: str, src_path: str) -> None:
        # streams from disk (multi-GB segments never materialize in RAM)
        self.client.put_file(self._key(backup_id, rel_path), src_path)

    def get_file(self, backup_id: str, rel_path: str, dst_path: str) -> None:
        if not self.client.get_to_file(
                self._key(backup_id, rel_path), dst_path):
            raise FileNotFoundError(f"{backup_id}/{rel_path}")

    def put_meta(self, backup_id: str, data: bytes) -> None:
        self.client.put(self._key(backup_id, "backup.json"), data)

    def get_meta(self, backup_id: str) -> Optional[bytes]:
        from weaviate_tpu.backup.object_store import ObjectStoreError

        try:
            return self.client.get(self._key(backup_id, "backup.json"))
        except ObjectStoreError:
            raise
        except (OSError, KeyError, ValueError):
            return None  # missing meta == backup does not exist

    def list_files(self, backup_id: str) -> list[str]:
        keys = self.client.list(validate_backup_id(backup_id) + "/")
        pre = backup_id + "/"
        meta = pre + "backup.json"  # exact meta key only — a data file
        # named *backup.json must survive the listing
        return sorted(k[len(pre):] for k in keys
                      if k.startswith(pre) and k != meta)


_REGISTRY: dict[str, type] = {"filesystem": FilesystemBackend}


def make_backend(name: str, root: str) -> BackupBackend:
    if name in ("s3", "gcs", "azure"):
        from weaviate_tpu.backup.object_store import make_client

        return ObjectStoreBackend(name, make_client(name))
    cls = _REGISTRY.get(name)
    if cls is None:
        raise KeyError(
            f"backup backend {name!r} not available (have: "
            f"{sorted(_REGISTRY) + ['s3', 'gcs', 'azure']})")
    return cls(root)
