"""Backups (reference ``usecases/backup`` + ``modules/backup-*``)."""

from weaviate_tpu.backup.backends import (
    BackupBackend,
    FilesystemBackend,
    make_backend,
)
from weaviate_tpu.backup.handler import BackupError, BackupHandler

__all__ = ["BackupBackend", "FilesystemBackend", "make_backend",
           "BackupHandler", "BackupError"]
