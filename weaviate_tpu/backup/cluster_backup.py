"""Snapshot-consistent cluster backup + point-in-time restore.

Reference: ``usecases/backup/coordinator.go`` — the coordinator drives
every participating node through a phase machine and only a terminal
global manifest makes the backup real. Mapped here onto the repo's own
primitives:

* the **fence** rides the WAL group-commit barrier
  (``storage/wal.py:sync_window``) and the shard checkpoint: a
  ``backup_fence`` RPC makes every write acked before the fence
  fsync-durable and checkpointed on every shard/replica;
* each node then uploads its fenced segment set + a per-node manifest
  (``backups/<id>/nodes/<node>/...``) to the shared blob store
  (``backup/blobstore.py``);
* the coordinator digest-verifies the uploads and writes the terminal
  cluster manifest ``backups/<id>/MANIFEST.json`` — the ATOMICITY
  point. A crash anywhere before it leaves a partial that can never
  restore (restore refuses without the terminal manifest) and that the
  retention sweep can GC; a crash after it leaves a complete backup.
* progress is journaled in the raft-replicated backup ledger
  (``cluster/fsm.py``), so a dead coordinator's partial is visible to
  every surviving node.

Restore replays the manifest into a DIFFERENT topology: collections are
re-created through raft, placement is computed by the rebalancer's pure
planner (``cluster/rebalance.py:plan_moves``) over the NEW cluster's
membership with per-shard byte weights from the manifest, and each
target node downloads, digest-verifies, and atomically installs its
assigned shards.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from typing import Optional

from weaviate_tpu.backup.blobstore import BlobStore, BlobStoreError
from weaviate_tpu.backup.handler import BackupError
from weaviate_tpu.cluster.rebalance import CrashInjected, plan_moves
from weaviate_tpu.cluster.resilience import Deadline
from weaviate_tpu.monitoring.metrics import (
    BACKUP_BYTES,
    BACKUP_RUNS,
    RESTORE_RUNS,
    RETENTION_DELETED,
)

logger = logging.getLogger("weaviate_tpu.backup.cluster")

BACKUP_PREFIX = "backups"
CLUSTER_MANIFEST = "MANIFEST.json"
NODE_MANIFEST = "manifest.json"


def cluster_manifest_key(backup_id: str) -> str:
    return f"{BACKUP_PREFIX}/{backup_id}/{CLUSTER_MANIFEST}"


def node_manifest_key(backup_id: str, node_id: str) -> str:
    return f"{BACKUP_PREFIX}/{backup_id}/nodes/{node_id}/{NODE_MANIFEST}"


def read_cluster_manifest(store: BlobStore, backup_id: str
                          ) -> Optional[dict]:
    """The terminal manifest, or None when the backup never committed
    (unknown id or a crashed coordinator's partial)."""
    try:
        return json.loads(store.get(cluster_manifest_key(backup_id)))
    except KeyError:
        return None
    except ValueError as e:
        raise BackupError(
            f"cluster manifest for {backup_id!r} is torn: {e}") from e


def verify_backup(store: BlobStore, manifest: dict) -> dict:
    """Digest-verify every blob the cluster manifest references, via the
    per-node manifests. Returns {node: parsed node manifest}. Raises
    :class:`BackupError` on any missing or corrupt blob — the gate both
    restore and the retention sweep run before acting."""
    nodes = {}
    for nid, info in manifest.get("nodes", {}).items():
        try:
            nm = json.loads(store.get(info["manifest_key"]))
        except KeyError:
            raise BackupError(
                f"backup {manifest['id']!r}: node manifest missing for "
                f"{nid}") from None
        except ValueError as e:
            raise BackupError(
                f"backup {manifest['id']!r}: node manifest for {nid} "
                f"is torn: {e}") from e
        for ent in nm.get("files", ()):
            try:
                data = store.get(ent["key"])
            except KeyError:
                raise BackupError(
                    f"backup {manifest['id']!r}: blob missing: "
                    f"{ent['key']}") from None
            if hashlib.sha256(data).hexdigest() != ent["sha256"]:
                raise BackupError(
                    f"backup {manifest['id']!r}: blob digest mismatch: "
                    f"{ent['key']}")
        nodes[nid] = nm
    return nodes


class ClusterBackupCoordinator:
    """Drives one cluster backup or restore from any node (the RPCs and
    ledger writes forward through raft/transport as usual).

    ``crash_points`` mirrors ``Rebalancer.crash_points``: the chaos
    suite plants a point name and the coordinator dies there with
    :class:`CrashInjected` — no cleanup, exactly a SIGKILL."""

    def __init__(self, node, store: BlobStore, *,
                 op_budget_s: float = 30.0,
                 crash_points: Optional[set] = None):
        self.node = node
        self.store = store
        self.op_budget_s = float(op_budget_s)
        self.crash_points = crash_points if crash_points is not None \
            else set()

    def _crash(self, point: str) -> None:
        if point in self.crash_points:
            raise CrashInjected(f"backup coordinator crash at {point!r}")

    def _advance(self, backup_id: str, state: str, **extra) -> None:
        res = self.node.apply({"op": "backup_advance", "id": backup_id,
                               "state": state, "ts": time.time(), **extra})
        if not res.get("ok"):
            raise BackupError(
                f"backup ledger advance to {state!r} failed: "
                f"{res.get('error')}")

    # -- backup ------------------------------------------------------------
    def backup(self, backup_id: str,
               include: Optional[list[str]] = None) -> dict:
        from weaviate_tpu.backup.backends import validate_backup_id

        try:
            validate_backup_id(backup_id)
        except ValueError as e:
            raise BackupError(str(e)) from e
        node = self.node
        classes = include or node.db.collections()
        for c in classes:
            if not node.db.has_collection(c):
                raise BackupError(f"class {c!r} not found")
        res = node.apply({"op": "backup_begin", "entry": {
            "id": backup_id, "classes": list(classes),
            "coordinator": node.id, "created_ts": time.time(),
        }})
        if not res.get("ok"):
            raise BackupError(res.get("error", "backup refused"))
        if "existing" in res:
            # idempotent re-submit of a committed backup
            return {"id": backup_id, "status": "SUCCESS",
                    "classes": res["existing"].get("classes", []),
                    "resubmitted": True}
        members = list(node.all_nodes)
        try:
            # phase 1 — the cluster-wide checkpoint fence: after this
            # fan-out, every write acked before backup() was called is
            # fsync-durable (WAL group-commit barrier) and checkpointed
            # on EVERY replica
            for peer in members:
                reply = node._call(peer, {
                    "type": "backup_fence", "backup_id": backup_id,
                    "classes": list(classes),
                }, deadline=Deadline(self.op_budget_s, op="backup_fence"),
                    timeout=self.op_budget_s)
                if not reply.get("ok"):
                    raise BackupError(
                        f"fence failed on {peer}: {reply.get('error')}")
            self._advance(backup_id, "uploading")
            self._crash("after_fence")
            # phase 2 — every node uploads its fenced segment set + a
            # per-node manifest
            total_bytes = 0
            node_infos = {}
            for i, peer in enumerate(members):
                reply = node._call(peer, {
                    "type": "backup_upload", "backup_id": backup_id,
                    "classes": list(classes),
                }, deadline=Deadline(self.op_budget_s * 4,
                                     op="backup_upload"),
                    timeout=self.op_budget_s * 4)
                if not reply.get("ok"):
                    raise BackupError(
                        f"upload failed on {peer}: {reply.get('error')}")
                info = {"manifest_key": reply["manifest_key"],
                        "files": reply["files"], "bytes": reply["bytes"]}
                node_infos[peer] = info
                total_bytes += reply["bytes"]
                self._advance(backup_id, "uploading", node=peer,
                              node_info=info)
                if i == 0:
                    self._crash("mid_upload")
            self._crash("before_commit")
            # the uploads are only trusted once every byte re-reads
            # correctly against its manifest digest
            manifest = {
                "id": backup_id, "version": 1,
                "created_at": time.time(),
                "coordinator": node.id,
                "members": members,
                "classes": {
                    cls: {
                        "config":
                            node.db.get_collection(cls).config.to_dict(),
                        "tenants":
                            node.db.get_collection(cls).tenants()
                            if node.db.get_collection(cls)
                            .config.multi_tenancy.enabled else {},
                    } for cls in classes
                },
                "nodes": node_infos,
            }
            verify_backup(self.store, manifest)
            # phase 3 — the terminal manifest IS the commit: atomic on
            # the blob store's single-key put
            self.store.put(cluster_manifest_key(backup_id),
                           json.dumps(manifest, sort_keys=True).encode())
            self._advance(backup_id, "committed",
                          manifest_key=cluster_manifest_key(backup_id))
        except CrashInjected:
            # a SIGKILLed coordinator runs NO cleanup: the ledger keeps
            # the non-terminal entry, the store keeps the partial
            raise
        except (BackupError, BlobStoreError, TimeoutError) as e:
            BACKUP_RUNS.inc(status="failed")
            try:
                self._advance(backup_id, "failed", error=str(e))
            except BackupError:
                logger.warning("backup %s: failed-state ledger advance "
                               "also failed", backup_id)
            raise BackupError(f"cluster backup {backup_id!r} failed: {e}") \
                from e
        BACKUP_RUNS.inc(status="success")
        BACKUP_BYTES.inc(total_bytes)
        logger.info("cluster backup %s committed (%d nodes, %d bytes)",
                    backup_id, len(members), total_bytes)
        return {"id": backup_id, "status": "SUCCESS",
                "classes": list(classes), "bytes": total_bytes,
                "nodes": sorted(node_infos)}

    # -- restore -----------------------------------------------------------
    def restore(self, backup_id: str,
                include: Optional[list[str]] = None) -> dict:
        node = self.node
        manifest = read_cluster_manifest(self.store, backup_id)
        if manifest is None:
            raise BackupError(
                f"backup {backup_id!r} has no committed cluster manifest "
                "(unknown id or a crashed coordinator's partial) — "
                "refusing to restore")
        node_manifests = verify_backup(self.store, manifest)
        classes = include or list(manifest["classes"].keys())
        from weaviate_tpu.backup.backends import validate_backup_id
        from weaviate_tpu.schema.config import CollectionConfig

        for cls in classes:
            try:
                validate_backup_id(cls)
            except ValueError:
                raise BackupError(
                    f"invalid class name in manifest: {cls!r}") from None
            if cls not in manifest["classes"]:
                raise BackupError(f"class {cls!r} not in backup")
            if node.db.has_collection(cls):
                raise BackupError(
                    f"class {cls!r} already exists; delete it before "
                    "restore")
        try:
            restored = []
            for cls in classes:
                entry = manifest["classes"][cls]
                cfg = CollectionConfig.from_dict(entry["config"])
                node.create_collection(cfg)
                # raft-submitted; a forwarding follower's local apply may
                # lag the leader's commit — bounded wait before placement
                wait_until = time.monotonic() + 10.0
                while not node.db.has_collection(cls) \
                        and time.monotonic() < wait_until:
                    time.sleep(0.02)
                placement = self._place(cls, cfg, node_manifests)
                for shard, (replicas, files) in placement.items():
                    for dst in replicas:
                        reply = node._call(dst, {
                            "type": "backup_install_shard",
                            "backup_id": backup_id, "class": cls,
                            "shard": shard, "files": files,
                        }, deadline=Deadline(self.op_budget_s * 4,
                                             op="backup_install"),
                            timeout=self.op_budget_s * 4)
                        if not reply.get("ok"):
                            raise BackupError(
                                f"install shard {cls}/{shard} on {dst} "
                                f"failed: {reply.get('error')}")
                if entry.get("tenants"):
                    node.add_tenants(cls, [
                        {"name": t, "status": s}
                        for t, s in entry["tenants"].items()])
                restored.append(cls)
        except (BackupError, BlobStoreError, TimeoutError) as e:
            RESTORE_RUNS.inc(status="failed")
            raise BackupError(
                f"cluster restore {backup_id!r} failed: {e}") from e
        RESTORE_RUNS.inc(status="success")
        logger.info("cluster restore %s complete (%s) into %d nodes",
                    backup_id, ",".join(restored), len(node.all_nodes))
        return {"id": backup_id, "status": "SUCCESS",
                "classes": restored}

    def _place(self, cls: str, cfg, node_manifests: dict
               ) -> dict[int, tuple[list[str], list[dict]]]:
        """shard -> (replica set on the NEW topology, source file list).

        Base placement comes from the new cluster's own sharding state;
        the rebalancer's pure planner then balances it with per-shard
        byte weights from the manifest (a 3-node backup restored into 5
        nodes spreads instead of landing on the first 3 ring slots).
        Planner moves are committed as raft routing overrides BEFORE any
        file lands, so routing and data always agree."""
        node = self.node
        state = node._state_for(cls)
        # per-shard source files: the node manifest with the most bytes
        # for a shard wins (the most complete fenced replica)
        sources: dict[int, tuple[int, list[dict]]] = {}
        for _nid, nm in sorted(node_manifests.items()):
            per_shard: dict[int, list[dict]] = {}
            for ent in nm.get("files", ()):
                if ent.get("class") != cls:
                    continue
                per_shard.setdefault(int(ent.get("shard", 0)),
                                     []).append(ent)
            for shard, files in per_shard.items():
                size = sum(int(f.get("size", 0)) for f in files)
                if shard not in sources or size > sources[shard][0]:
                    sources[shard] = (size, files)
        placement = {s: state.replicas(s) for s in sources}
        snapshot = {
            "nodes": list(node.all_nodes),
            "draining": list(node.fsm.draining_nodes),
            "meta": {},
            "shards": [
                {"class": cls, "shard": s, "replicas": placement[s],
                 "weight": max(1.0, float(sources[s][0]))}
                for s in sorted(sources)
            ],
        }
        for mv in plan_moves(snapshot, max_moves=4 * len(sources)):
            reps = [mv.dst if r == mv.src else r
                    for r in placement[mv.shard]]
            res = node.apply({"op": "set_shard_replicas", "class": cls,
                              "shard": mv.shard, "nodes": reps})
            if not res.get("ok"):
                raise BackupError(
                    f"routing override for {cls}/{mv.shard} failed: "
                    f"{res.get('error')}")
            placement[mv.shard] = reps
        return {s: (placement[s], sources[s][1]) for s in sources}


# -- retention / orphan sweep ----------------------------------------------
def referenced_backup_keys(store: BlobStore) -> set:
    """Every key a COMMITTED cluster manifest still references (manifests
    included): the never-delete allow-list."""
    out: set = set()
    for key in store.list(f"{BACKUP_PREFIX}/"):
        parts = key.split("/")
        if len(parts) != 3 or parts[2] != CLUSTER_MANIFEST:
            continue
        try:
            man = json.loads(store.get(key))
        except (KeyError, ValueError, BlobStoreError):
            continue
        out.add(key)
        for info in man.get("nodes", {}).values():
            mkey = info.get("manifest_key", "")
            out.add(mkey)
            try:
                nm = json.loads(store.get(mkey))
            except (KeyError, ValueError, BlobStoreError):
                continue
            for ent in nm.get("files", ()):
                out.add(ent.get("key"))
    return out


def _delete_partial_backup(store: BlobStore, keys: list) -> int:
    """Deletion primitive for a crashed coordinator's partial: there is
    no manifest to verify by construction (the terminal manifest's
    absence is WHY it may die), and the caller only reaches here for ids
    the operator/ledger explicitly named dead."""
    n = 0
    for key in keys:
        store.delete(key)
        RETENTION_DELETED.inc(reason="partial_backup")
        n += 1
    return n


def sweep_backups(store: BlobStore, delete_ids: tuple = ()) -> int:
    """GC the backup prefix. Two classes of garbage:

    * keys under a COMMITTED backup that its manifests do not reference
      (leftovers of retried uploads) — deleted only after the backup
      re-verifies intact;
    * entire partials named in ``delete_ids`` (a crashed coordinator's
      backup the operator or ledger declared dead) — refused if the id
      actually committed.

    Keys a committed manifest references are NEVER deleted."""
    deleted = 0
    referenced = referenced_backup_keys(store)
    by_id: dict[str, list[str]] = {}
    for key in store.list(f"{BACKUP_PREFIX}/"):
        parts = key.split("/")
        if len(parts) >= 3:
            by_id.setdefault(parts[1], []).append(key)
    for bid, keys in sorted(by_id.items()):
        manifest = read_cluster_manifest(store, bid)
        if manifest is None:
            if bid not in delete_ids:
                continue  # possibly in flight: only named partials die
            deleted += _delete_partial_backup(store, keys)
            continue
        if bid in delete_ids:
            logger.warning("sweep: refusing to delete committed backup "
                           "%s", bid)
        # committed: verify FIRST, then drop only unreferenced strays
        try:
            verify_backup(store, manifest)
        except BackupError as e:
            logger.warning("sweep: backup %s fails verification (%s); "
                           "leaving its keys untouched", bid, e)
            continue
        for key in keys:
            if key in referenced:
                continue
            store.delete(key)
            RETENTION_DELETED.inc(reason="unreferenced")
            deleted += 1
    return deleted
