"""Object-store clients: S3 (SigV4), GCS (JSON API), Azure Blob (SharedKey).

Reference: ``modules/backup-{s3,gcs,azure}`` + ``modules/offload-s3`` +
``modules/usage-{s3,gcs}`` wrap the vendor SDKs. This environment has no
SDKs, so the three wire protocols are implemented directly over urllib —
S3's AWS SigV4 request signing and Azure's SharedKey authorization are
pure hashlib/hmac; GCS authenticates with a bearer token (service-account
JWT exchange needs RSA signing, which stdlib lacks — deployments supply
``GCP_ACCESS_TOKEN`` the way workload identity would).

The HTTP layer is injectable (``http(method, url, headers, body) ->
(status, body)``) so tests run against an in-process emulator and the
signing/URL construction is still exercised end to end.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

HttpFn = Callable[[str, str, dict, bytes], tuple[int, bytes]]


class ObjectStoreError(RuntimeError):
    pass


def urllib_http(method: str, url: str, headers: dict,
                body) -> tuple[int, bytes]:
    """``body`` may be bytes or a file-like object (uploads stream from
    disk instead of materializing multi-GB segments in RAM; callers set
    Content-Length for file bodies)."""
    req = urllib.request.Request(url, data=body if body else None,
                                 headers=headers, method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except (urllib.error.URLError, OSError) as e:
        raise ObjectStoreError(f"object store unreachable: {url}: {e}")


def _sha256_file(path: str) -> tuple[str, int]:
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
            n += len(chunk)
    return h.hexdigest(), n


def _urllib_get_to_file(url: str, headers: dict, dst: str) -> bool:
    """Chunked GET → file (downloads never materialize whole objects)."""
    import shutil as _shutil

    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            tmp = dst + ".dl"
            with open(tmp, "wb") as f:
                _shutil.copyfileobj(r, f, 1 << 20)
            os.replace(tmp, dst)
            return True
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return False
        raise ObjectStoreError(f"get {url}: HTTP {e.code}")
    except (urllib.error.URLError, OSError) as e:
        raise ObjectStoreError(f"object store unreachable: {url}: {e}")


class ObjectStoreClient:
    """put/get/delete/list over a bucket-like container."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    # file-path variants so multi-GB segment files stream instead of
    # materializing in RAM; subclasses override when the wire protocol
    # allows a file-like body (custom test transports use these defaults)
    def put_file(self, key: str, path: str) -> None:
        with open(path, "rb") as f:
            self.put(key, f.read())

    def get_to_file(self, key: str, dst: str) -> bool:
        data = self.get(key)
        if data is None:
            return False
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        with open(dst, "wb") as f:
            f.write(data)
        return True


def _hmac256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Client(ObjectStoreClient):
    """AWS SigV4-signed S3 REST (virtual-host or path style)."""

    def __init__(self, bucket: str, region: str = "us-east-1",
                 access_key: str = "", secret_key: str = "",
                 endpoint: str = "", http: Optional[HttpFn] = None):
        self.bucket = bucket
        self.region = region
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        # custom endpoint (minio/emulator) uses path-style addressing
        self.endpoint = endpoint.rstrip("/") if endpoint else \
            f"https://{bucket}.s3.{region}.amazonaws.com"
        self.path_style = bool(endpoint)
        self.http = http or urllib_http

    def _sign(self, method: str, path: str, query: str,
              payload: bytes, payload_hash: str = "") -> dict:
        now = datetime.datetime.now(datetime.timezone.utc)
        amzdate = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.endpoint).netloc
        payload_hash = payload_hash or hashlib.sha256(payload).hexdigest()
        canonical_headers = (f"host:{host}\n"
                             f"x-amz-content-sha256:{payload_hash}\n"
                             f"x-amz-date:{amzdate}\n")
        signed = "host;x-amz-content-sha256;x-amz-date"
        creq = "\n".join([method, path, query, canonical_headers, signed,
                          payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                         hashlib.sha256(creq.encode()).hexdigest()])
        k = _hmac256(("AWS4" + self.secret_key).encode(), datestamp)
        k = _hmac256(k, self.region)
        k = _hmac256(k, "s3")
        k = _hmac256(k, "aws4_request")
        sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
        return {
            "x-amz-date": amzdate,
            "x-amz-content-sha256": payload_hash,
            "Authorization": (
                f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders={signed}, Signature={sig}"),
        }

    def _request(self, method: str, key: str, query: str = "",
                 body: bytes = b"") -> tuple[int, bytes]:
        kpath = urllib.parse.quote(key, safe="/~-._")
        path = (f"/{self.bucket}/{kpath}" if self.path_style
                else f"/{kpath}").rstrip("/") or "/"
        headers = self._sign(method, path, query, body)
        url = self.endpoint + path + (f"?{query}" if query else "")
        return self.http(method, url, headers, body)

    def put(self, key: str, data: bytes) -> None:
        status, body = self._request("PUT", key, body=data)
        if status not in (200, 201):
            raise ObjectStoreError(f"s3 put {key}: HTTP {status} {body[:200]}")

    def put_file(self, key: str, path: str) -> None:
        if self.http is not urllib_http:
            return super().put_file(key, path)
        phash, length = _sha256_file(path)
        kpath = urllib.parse.quote(key, safe="/~-._")
        upath = (f"/{self.bucket}/{kpath}" if self.path_style
                 else f"/{kpath}")
        headers = self._sign("PUT", upath, "", b"", payload_hash=phash)
        headers["Content-Length"] = str(length)
        with open(path, "rb") as f:
            status, body = urllib_http(
                "PUT", self.endpoint + upath, headers, f)
        if status not in (200, 201):
            raise ObjectStoreError(f"s3 put {key}: HTTP {status}")

    def get_to_file(self, key: str, dst: str) -> bool:
        if self.http is not urllib_http:
            return super().get_to_file(key, dst)
        kpath = urllib.parse.quote(key, safe="/~-._")
        upath = f"/{self.bucket}/{kpath}" if self.path_style else f"/{kpath}"
        headers = self._sign("GET", upath, "", b"")
        return _urllib_get_to_file(self.endpoint + upath, headers, dst)

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise ObjectStoreError(f"s3 get {key}: HTTP {status}")
        return body

    def delete(self, key: str) -> None:
        status, _ = self._request("DELETE", key)
        if status not in (200, 204, 404):
            raise ObjectStoreError(f"s3 delete {key}: HTTP {status}")

    def list(self, prefix: str) -> list[str]:
        # ListObjectsV2 with continuation-token pagination (a truncated
        # listing silently dropping keys would make restores partial);
        # query params must be canonical-sorted for SigV4
        import re

        keys: list[str] = []
        token = ""
        while True:
            parts = ["list-type=2",
                     "prefix=" + urllib.parse.quote(prefix, safe="")]
            if token:
                parts.append("continuation-token="
                             + urllib.parse.quote(token, safe=""))
            q = "&".join(sorted(parts))
            status, body = self._request("GET", "", query=q)
            if status != 200:
                raise ObjectStoreError(f"s3 list {prefix}: HTTP {status}")
            text = body.decode()
            keys.extend(re.findall(r"<Key>([^<]+)</Key>", text))
            m = re.search(r"<NextContinuationToken>([^<]+)"
                          r"</NextContinuationToken>", text)
            if not m or "<IsTruncated>true</IsTruncated>" not in text:
                break
            token = m.group(1)
        return keys


class GCSClient(ObjectStoreClient):
    """GCS JSON API with bearer-token auth."""

    def __init__(self, bucket: str, token: str = "", endpoint: str = "",
                 http: Optional[HttpFn] = None):
        self.bucket = bucket
        self.token = token or os.environ.get("GCP_ACCESS_TOKEN", "")
        self.endpoint = (endpoint.rstrip("/")
                         or "https://storage.googleapis.com")
        self.http = http or urllib_http

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} if self.token else {}

    def put(self, key: str, data: bytes) -> None:
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name={urllib.parse.quote(key, safe='')}")
        status, body = self.http("POST", url, self._headers(), data)
        if status not in (200, 201):
            raise ObjectStoreError(f"gcs put {key}: HTTP {status}")

    def get(self, key: str) -> Optional[bytes]:
        url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}?alt=media")
        status, body = self.http("GET", url, self._headers(), b"")
        if status == 404:
            return None
        if status != 200:
            raise ObjectStoreError(f"gcs get {key}: HTTP {status}")
        return body

    def delete(self, key: str) -> None:
        url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}")
        status, _ = self.http("DELETE", url, self._headers(), b"")
        if status not in (200, 204, 404):
            raise ObjectStoreError(f"gcs delete {key}: HTTP {status}")

    def put_file(self, key: str, path: str) -> None:
        if self.http is not urllib_http:
            return super().put_file(key, path)
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name={urllib.parse.quote(key, safe='')}")
        headers = dict(self._headers())
        headers["Content-Length"] = str(os.path.getsize(path))
        with open(path, "rb") as f:
            status, _ = urllib_http("POST", url, headers, f)
        if status not in (200, 201):
            raise ObjectStoreError(f"gcs put {key}: HTTP {status}")

    def get_to_file(self, key: str, dst: str) -> bool:
        if self.http is not urllib_http:
            return super().get_to_file(key, dst)
        url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}?alt=media")
        return _urllib_get_to_file(url, self._headers(), dst)

    def list(self, prefix: str) -> list[str]:
        keys: list[str] = []
        token = ""
        while True:
            url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o"
                   f"?prefix={urllib.parse.quote(prefix, safe='')}")
            if token:
                url += f"&pageToken={urllib.parse.quote(token, safe='')}"
            status, body = self.http("GET", url, self._headers(), b"")
            if status != 200:
                raise ObjectStoreError(f"gcs list {prefix}: HTTP {status}")
            out = json.loads(body)
            keys.extend(it["name"] for it in out.get("items", []))
            token = out.get("nextPageToken", "")
            if not token:
                break
        return keys


class AzureClient(ObjectStoreClient):
    """Azure Blob REST with SharedKey authorization."""

    VERSION = "2021-08-06"

    def __init__(self, account: str, container: str, key: str = "",
                 endpoint: str = "", http: Optional[HttpFn] = None):
        self.account = account
        self.container = container
        self.key = key or os.environ.get("AZURE_STORAGE_KEY", "")
        self.endpoint = (endpoint.rstrip("/")
                         or f"https://{account}.blob.core.windows.net")
        self.http = http or urllib_http

    def _auth(self, method: str, path: str, query: dict,
              length: int, extra_ms: dict) -> dict:
        now = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT")
        ms = {"x-ms-date": now, "x-ms-version": self.VERSION, **extra_ms}
        canon_headers = "".join(
            f"{k}:{v}\n" for k, v in sorted(ms.items()))
        canon_resource = f"/{self.account}{path}" + "".join(
            f"\n{k}:{v}" for k, v in sorted(query.items()))
        sts = "\n".join([
            method, "", "", str(length) if length else "", "", "", "", "",
            "", "", "", "", canon_headers + canon_resource])
        sig = base64.b64encode(hmac.new(
            base64.b64decode(self.key) if self.key else b"",
            sts.encode(), hashlib.sha256).digest()).decode()
        return {**ms, "Authorization": f"SharedKey {self.account}:{sig}"}

    def _request(self, method: str, blob: str, query: dict,
                 body: bytes = b"", extra_ms: Optional[dict] = None
                 ) -> tuple[int, bytes]:
        bpath = urllib.parse.quote(blob, safe="/~-._")
        path = f"/{self.container}/{bpath}" if blob else f"/{self.container}"
        headers = self._auth(method, path, query, len(body), extra_ms or {})
        qs = urllib.parse.urlencode(query)
        url = self.endpoint + path + (f"?{qs}" if qs else "")
        return self.http(method, url, headers, body)

    def put(self, key: str, data: bytes) -> None:
        status, body = self._request(
            "PUT", key, {}, data, {"x-ms-blob-type": "BlockBlob"})
        if status not in (200, 201):
            raise ObjectStoreError(f"azure put {key}: HTTP {status}")

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._request("GET", key, {})
        if status == 404:
            return None
        if status != 200:
            raise ObjectStoreError(f"azure get {key}: HTTP {status}")
        return body

    def delete(self, key: str) -> None:
        status, _ = self._request("DELETE", key, {})
        if status not in (200, 202, 204, 404):
            raise ObjectStoreError(f"azure delete {key}: HTTP {status}")

    def put_file(self, key: str, path: str) -> None:
        if self.http is not urllib_http:
            return super().put_file(key, path)
        length = os.path.getsize(path)
        bpath = urllib.parse.quote(key, safe="/~-._")
        upath = f"/{self.container}/{bpath}"
        headers = self._auth("PUT", upath, {}, length,
                             {"x-ms-blob-type": "BlockBlob"})
        headers["Content-Length"] = str(length)
        with open(path, "rb") as f:
            status, _ = urllib_http(
                "PUT", self.endpoint + upath, headers, f)
        if status not in (200, 201):
            raise ObjectStoreError(f"azure put {key}: HTTP {status}")

    def get_to_file(self, key: str, dst: str) -> bool:
        if self.http is not urllib_http:
            return super().get_to_file(key, dst)
        bpath = urllib.parse.quote(key, safe="/~-._")
        upath = f"/{self.container}/{bpath}"
        headers = self._auth("GET", upath, {}, 0, {})
        return _urllib_get_to_file(self.endpoint + upath, headers, dst)

    def list(self, prefix: str) -> list[str]:
        import re

        keys: list[str] = []
        marker = ""
        while True:
            q = {"comp": "list", "prefix": prefix, "restype": "container"}
            if marker:
                q["marker"] = marker
            status, body = self._request("GET", "", q)
            if status != 200:
                raise ObjectStoreError(f"azure list {prefix}: HTTP {status}")
            text = body.decode()
            keys.extend(re.findall(r"<Name>([^<]+)</Name>", text))
            m = re.search(r"<NextMarker>([^<]+)</NextMarker>", text)
            if not m:
                break
            marker = m.group(1)
        return keys


def make_client(provider: str, http: Optional[HttpFn] = None
                ) -> ObjectStoreClient:
    """Env-configured client (reference module env vars:
    BACKUP_S3_BUCKET/BACKUP_GCS_BUCKET/BACKUP_AZURE_CONTAINER...). An
    unconfigured provider raises KeyError so API handlers answer 422, the
    same as a reference deployment without the module enabled."""
    if provider == "s3":
        bucket = os.environ.get("BACKUP_S3_BUCKET", "")
        if not bucket:
            raise KeyError("backup backend 's3' not configured "
                           "(set BACKUP_S3_BUCKET)")
        return S3Client(
            bucket=bucket,
            region=os.environ.get("AWS_REGION", "us-east-1"),
            endpoint=os.environ.get("BACKUP_S3_ENDPOINT", ""),
            http=http)
    if provider == "gcs":
        bucket = os.environ.get("BACKUP_GCS_BUCKET", "")
        if not bucket:
            raise KeyError("backup backend 'gcs' not configured "
                           "(set BACKUP_GCS_BUCKET)")
        return GCSClient(
            bucket=bucket,
            endpoint=os.environ.get("BACKUP_GCS_ENDPOINT", ""),
            http=http)
    if provider == "azure":
        container = os.environ.get("BACKUP_AZURE_CONTAINER", "")
        if not container:
            raise KeyError("backup backend 'azure' not configured "
                           "(set BACKUP_AZURE_CONTAINER)")
        return AzureClient(
            account=os.environ.get("AZURE_STORAGE_ACCOUNT", ""),
            container=container,
            endpoint=os.environ.get("BACKUP_AZURE_ENDPOINT", ""),
            http=http)
    raise KeyError(f"unknown object-store provider {provider!r}")
