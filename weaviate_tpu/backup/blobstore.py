"""S3-shaped blob tier: the durability layer below the disk tier.

Reference: the ``modules/offload-s3`` bucket the reference parks FROZEN
tenants in, generalized into the flat put/get/list/delete surface every
cold-tier consumer here shares (``tiering/coldstore.py`` wholesale tenant
offload, ``backup/cluster_backup.py`` snapshot backups, the retention
sweep). Two implementations ship: a local-directory fake that is fully
functional (and what the zero-egress test image runs), and an adapter
over ``backup/object_store.py``'s real S3/GCS/Azure clients.

:class:`FaultInjectingBlobStore` wraps any store with seeded,
programmable per-op faults — drop, latency, torn writes — in the style
of ``cluster/chaos.py:ChaosTransport``. The chaos suites drive offload
and backup through it to prove the manifest-first / verify-then-delete
protocols hold when the bucket misbehaves.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, replace
from typing import Optional

from weaviate_tpu.monitoring.metrics import CHAOS_FAULTS


class BlobStoreError(RuntimeError):
    """A blob operation failed (injected fault, backend error, torn
    write). Retryable at the caller's discretion — the offload/backup
    protocols wrap ops in ``cluster/resilience.retrying_call``."""


def validate_key(key: str) -> str:
    """Blob keys are ``/``-joined posix-ish components: no traversal, no
    absolute paths, no empty segments. Keys cross trust boundaries (a
    restore reads them out of a manifest an attacker may have written),
    so every store validates on BOTH read and write."""
    if not key or key.startswith("/") or key.endswith("/"):
        raise BlobStoreError(f"invalid blob key {key!r}")
    parts = key.split("/")
    if any(p in ("", ".", "..") for p in parts):
        raise BlobStoreError(f"invalid blob key {key!r}")
    return key


class BlobStore:
    """SPI: a flat keyspace of immutable-ish blobs."""

    name = "blob"

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        """Return the blob or raise :class:`KeyError` when absent."""
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        """All keys under ``prefix``, sorted."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Idempotent: deleting a missing key is a no-op."""
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        try:
            self.get(key)
            return True
        except KeyError:
            return False

    # -- file-shaped convenience (segments are files on both ends) -------
    def put_file(self, key: str, src_path: str) -> None:
        with open(src_path, "rb") as f:
            self.put(key, f.read())

    def get_to_file(self, key: str, dst_path: str) -> None:
        data = self.get(key)
        os.makedirs(os.path.dirname(dst_path) or ".", exist_ok=True)
        tmp = dst_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, dst_path)


class LocalDirBlobStore(BlobStore):
    """The local-dir fake: one file per key under ``root``. Writes are
    atomic (tmp + ``os.replace``) so a crashed writer never leaves a
    half-blob a reader could mistake for the real thing — torn blobs
    exist in this tree only when the fault injector tears them on
    purpose."""

    name = "localdir"

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *validate_key(key).split("/"))

    def put(self, key: str, data: bytes) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = f"{p}.tmp.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, p)
        except OSError as e:
            raise BlobStoreError(f"put {key!r}: {e}") from e

    def get(self, key: str) -> bytes:
        p = self._path(key)
        try:
            with open(p, "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(key) from None
        except OSError as e:
            raise BlobStoreError(f"get {key!r}: {e}") from e

    def list(self, prefix: str = "") -> list[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                full = os.path.join(dirpath, fn)
                key = os.path.relpath(full, self.root).replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass
        except OSError as e:
            raise BlobStoreError(f"delete {key!r}: {e}") from e

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))


class ObjectStoreBlobStore(BlobStore):
    """Adapter over ``backup/object_store.py`` clients (S3 SigV4 / GCS /
    Azure): the same wire clients the backup backends use, re-shaped to
    the flat BlobStore SPI. Client errors surface as
    :class:`BlobStoreError` so callers retry uniformly."""

    name = "objectstore"

    def __init__(self, client):
        self.client = client

    def put(self, key: str, data: bytes) -> None:
        from weaviate_tpu.backup.object_store import ObjectStoreError

        try:
            self.client.put(validate_key(key), data)
        except ObjectStoreError as e:
            raise BlobStoreError(str(e)) from e

    def get(self, key: str) -> bytes:
        from weaviate_tpu.backup.object_store import ObjectStoreError

        try:
            data = self.client.get(validate_key(key))
        except ObjectStoreError as e:
            raise BlobStoreError(str(e)) from e
        if data is None:
            raise KeyError(key)
        return data

    def list(self, prefix: str = "") -> list[str]:
        from weaviate_tpu.backup.object_store import ObjectStoreError

        try:
            return sorted(self.client.list(prefix))
        except ObjectStoreError as e:
            raise BlobStoreError(str(e)) from e

    def delete(self, key: str) -> None:
        from weaviate_tpu.backup.object_store import ObjectStoreError

        try:
            self.client.delete(validate_key(key))
        except ObjectStoreError as e:
            raise BlobStoreError(str(e)) from e


@dataclass
class BlobFaults:
    """One op-class's fault program (``ChaosTransport.LinkFaults`` for
    the bucket): probabilities are per OPERATION, decided by one rng draw
    each under the lock so a seeded schedule is deterministic."""

    drop: float = 0.0        # raise BlobStoreError, op not performed
    torn_write: float = 0.0  # put writes a truncated prefix, then raises
    latency: float = 0.0     # fixed pre-op delay (seconds)
    jitter: float = 0.0      # + uniform(0, jitter)


class FaultInjectingBlobStore(BlobStore):
    """Seeded fault wrapper for any :class:`BlobStore`.

    ``program(op, **faults)`` installs a fault program for one op
    (``put``/``get``/``list``/``delete``) or, with ``op=None``, for all
    of them; ``clear()`` resets. A torn write is the nasty case: the
    inner store receives a truncated prefix of the data and the caller
    sees a failure — the blob EXISTS but is corrupt, which is exactly
    what digest verification (and nothing else) catches.
    """

    name = "chaosblob"

    _OPS = ("put", "get", "list", "delete")

    def __init__(self, inner: BlobStore, seed: int = 0):
        self.inner = inner
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._programs: dict[str, BlobFaults] = {}
        self.faults_fired = 0

    def program(self, op: Optional[str] = None, **kw) -> None:
        """Install/extend the fault program for ``op`` (None = all)."""
        ops = self._OPS if op is None else (op,)
        with self._lock:
            for o in ops:
                if o not in self._OPS:
                    raise ValueError(f"unknown blob op {o!r}")
                cur = self._programs.get(o, BlobFaults())
                self._programs[o] = replace(cur, **kw)

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()

    def _decide(self, op: str, key: str) -> tuple[bool, bool, float]:
        """(drop?, torn?, delay) — one rng draw per probability, under
        the lock, so concurrent ops cannot reorder a seeded schedule."""
        with self._lock:
            f = self._programs.get(op)
            if f is None:
                return False, False, 0.0
            drop = f.drop > 0 and self._rng.random() < f.drop
            torn = (op == "put" and not drop and f.torn_write > 0
                    and self._rng.random() < f.torn_write)
            delay = f.latency + (
                self._rng.random() * f.jitter if f.jitter > 0 else 0.0)
        if drop:
            self.faults_fired += 1
            CHAOS_FAULTS.inc(kind="blob_drop", link=f"{op}:{key}")
        if torn:
            self.faults_fired += 1
            CHAOS_FAULTS.inc(kind="blob_torn_write", link=f"{op}:{key}")
        return drop, torn, delay

    def put(self, key: str, data: bytes) -> None:
        drop, torn, delay = self._decide("put", key)
        if delay > 0:
            time.sleep(delay)
        if drop:
            raise BlobStoreError(f"injected drop: put {key!r}")
        if torn:
            # the inner store sees a PREFIX commit: the key exists with
            # truncated bytes, the caller sees a failure — only a digest
            # check can tell this apart from a good blob
            self.inner.put(key, data[: max(0, len(data) // 2)])
            raise BlobStoreError(f"injected torn write: put {key!r}")
        self.inner.put(key, data)

    def get(self, key: str) -> bytes:
        drop, _torn, delay = self._decide("get", key)
        if delay > 0:
            time.sleep(delay)
        if drop:
            raise BlobStoreError(f"injected drop: get {key!r}")
        return self.inner.get(key)

    def list(self, prefix: str = "") -> list[str]:
        drop, _torn, delay = self._decide("list", prefix)
        if delay > 0:
            time.sleep(delay)
        if drop:
            raise BlobStoreError(f"injected drop: list {prefix!r}")
        return self.inner.list(prefix)

    def delete(self, key: str) -> None:
        drop, _torn, delay = self._decide("delete", key)
        if delay > 0:
            time.sleep(delay)
        if drop:
            raise BlobStoreError(f"injected drop: delete {key!r}")
        self.inner.delete(key)

    def exists(self, key: str) -> bool:
        return self.inner.exists(key)


def make_blobstore() -> Optional[BlobStore]:
    """Environment-gated factory for the cold/backup blob tier.

    ``COLD_TIER_BLOB_PATH`` selects the local-dir store (tests, single
    boxes, NFS); ``COLD_TIER_S3_BUCKET`` the S3 client (same env surface
    as ``backup/object_store.py``). Absent both, there is no blob tier
    and offload/cluster-backup features stay dormant.
    """
    path = os.environ.get("COLD_TIER_BLOB_PATH")
    if path:
        return LocalDirBlobStore(path)
    if os.environ.get("COLD_TIER_S3_BUCKET"):
        from weaviate_tpu.backup.object_store import S3Client

        return ObjectStoreBlobStore(
            S3Client(bucket=os.environ["COLD_TIER_S3_BUCKET"]))
    return None
