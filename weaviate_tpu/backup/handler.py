"""Backup coordinator: create/status/restore.

Reference: ``usecases/backup/{handler,coordinator,backupper,restorer}.go`` —
create flushes each included collection, snapshots its files to the backend
with a meta manifest (status PENDING→TRANSFERRING→SUCCESS like the
reference's state machine), restore copies files back and reloads the
collections. Single-node scope here; the reference's multi-participant
coordination rides the cluster layer later.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Optional

from weaviate_tpu.backup.backends import BackupBackend
from weaviate_tpu.core.db import DB
from weaviate_tpu.version import __version__

STATUS_STARTED = "STARTED"
STATUS_TRANSFERRING = "TRANSFERRING"
STATUS_SUCCESS = "SUCCESS"
STATUS_FAILED = "FAILED"


class BackupError(RuntimeError):
    pass


class BackupHandler:
    def __init__(self, db: DB):
        self.db = db
        self._lock = threading.Lock()
        self._active: dict[str, dict] = {}  # backup_id -> live status

    # -- create ------------------------------------------------------------
    def create(self, backend: BackupBackend, backup_id: str,
               include: Optional[list[str]] = None,
               exclude: Optional[list[str]] = None,
               wait: bool = True) -> dict:
        classes = include or self.db.collections()
        classes = [c for c in classes if c not in (exclude or [])]
        for c in classes:
            if not self.db.has_collection(c):
                raise BackupError(f"class {c!r} not found")
        status = {
            "id": backup_id, "backend": backend.name,
            "status": STATUS_STARTED, "classes": classes,
            "version": __version__, "started_at": time.time(),
            "error": None, "class_errors": {},
        }
        # idempotent re-submit (reference: repeated POST of the same
        # backup id must not fork a second copy): an in-flight or
        # already-stored backup answers with ITS status instead of
        # starting over. The backend probe is blocking I/O, so it runs
        # BEFORE the lock; the _active check under the lock stays the
        # same-process arbiter (a FAILED entry may be retried).
        prior: Optional[dict] = None
        if backend.exists(backup_id):
            meta = backend.get_meta(backup_id)
            prior = (json.loads(meta).get("status") if meta else None) \
                or {"id": backup_id, "status": STATUS_SUCCESS}
        with self._lock:
            live = self._active.get(backup_id)
            if live is not None and live["status"] != STATUS_FAILED:
                return dict(live)
            if prior is not None:
                return dict(prior)
            self._active[backup_id] = status

        def run():
            try:
                status["status"] = STATUS_TRANSFERRING
                manifest: dict = {"classes": {}, "version": __version__}
                for cls in classes:
                    try:
                        self._copy_class(backend, backup_id, cls, manifest)
                    except Exception as e:  # noqa: BLE001 — per-class
                        # one broken class must not mask the rest: record
                        # WHICH copy failed and keep going, so status()
                        # surfaces per-class error detail
                        status["class_errors"][cls] = str(e)
                if status["class_errors"]:
                    raise BackupError(
                        "class copies failed: " + "; ".join(
                            f"{c}: {m}" for c, m in
                            sorted(status["class_errors"].items())))
                status["status"] = STATUS_SUCCESS
                status["completed_at"] = time.time()
                manifest["status"] = status
                backend.put_meta(
                    backup_id, json.dumps(manifest).encode())
            except Exception as e:  # backup must never crash the server
                status["status"] = STATUS_FAILED
                status["error"] = str(e)
                status["completed_at"] = time.time()

        if wait:
            run()
        else:
            threading.Thread(target=run, daemon=True).start()
        return dict(status)

    def _copy_class(self, backend: BackupBackend, backup_id: str,
                    cls: str, manifest: dict) -> None:
        col = self.db.get_collection(cls)
        col.flush()
        # freeze the segment set while walking+copying: a concurrent
        # compaction would delete listed files mid-copy (reference
        # bucket_pauses.go)
        with col.maintenance_paused():
            files = []
            base = col.dir
            for dirpath, _dirs, fnames in os.walk(base):
                for fn in fnames:
                    full = os.path.join(dirpath, fn)
                    rel = os.path.join(
                        cls, os.path.relpath(full, base))
                    backend.put_file(backup_id, rel, full)
                    files.append(rel)
            # FROZEN tenants live in the local offload tier, outside
            # col.dir — without these files a restore would recreate the
            # tenant FROZEN but empty. (Bucket-offloaded tenants already
            # sit in durable object storage; the manifest records that.)
            frozen_root = col._offload_root()
            offloaded = []
            from weaviate_tpu.backup.offload import get_offloader

            bucket_off = get_offloader()
            for tname, tstatus in col.tenants().items():
                if tstatus != "FROZEN":
                    continue
                fdir = os.path.join(frozen_root, tname)
                if os.path.isdir(fdir):
                    for dirpath, _dirs, fnames in os.walk(fdir):
                        for fn in fnames:
                            full = os.path.join(dirpath, fn)
                            rel = os.path.join(
                                cls, "__frozen__", tname,
                                os.path.relpath(full, fdir))
                            backend.put_file(backup_id, rel, full)
                            files.append(rel)
                elif bucket_off is not None and \
                        bucket_off.exists(cls, tname):
                    offloaded.append(tname)
        manifest["classes"][cls] = {
            "config": col.config.to_dict(),
            "files": files,
            "tenants": col.tenants(),
            # frozen tenants whose data stays in the offload bucket (not
            # copied into the backup)
            "bucket_offloaded_tenants": offloaded,
        }

    def status(self, backend: BackupBackend, backup_id: str) -> dict:
        with self._lock:
            live = self._active.get(backup_id)
        if live is not None:
            return dict(live)
        meta = backend.get_meta(backup_id)
        if meta is None:
            raise KeyError(f"backup {backup_id!r} not found")
        return json.loads(meta).get("status", {})

    # -- restore -----------------------------------------------------------
    def restore(self, backend: BackupBackend, backup_id: str,
                include: Optional[list[str]] = None,
                exclude: Optional[list[str]] = None) -> dict:
        meta = backend.get_meta(backup_id)
        if meta is None:
            raise BackupError(f"backup {backup_id!r} not found")
        manifest = json.loads(meta)
        classes = include or list(manifest["classes"].keys())
        classes = [c for c in classes if c not in (exclude or [])]
        from weaviate_tpu.schema.config import CollectionConfig

        # validate ALL classes before touching the DB (no partial restores);
        # class names come from the (untrusted) manifest — a name like
        # '../../x' must never reach os.path.join(self.db.root, cls)
        from weaviate_tpu.backup.backends import validate_backup_id

        for cls in classes:
            try:
                validate_backup_id(cls)
            except ValueError:
                raise BackupError(f"invalid class name in manifest: {cls!r}")
            if manifest["classes"].get(cls) is None:
                raise BackupError(f"class {cls!r} not in backup")
            if self.db.has_collection(cls):
                raise BackupError(
                    f"class {cls!r} already exists; delete it before restore")

        restored = []
        for cls in classes:
            entry = manifest["classes"][cls]
            target_dir = os.path.join(self.db.root, cls)
            tmp_dir = target_dir + ".restore"
            shutil.rmtree(tmp_dir, ignore_errors=True)
            from weaviate_tpu.backup.backends import confine

            frozen_prefix = os.path.join(cls, "__frozen__")
            offload_base = os.environ.get(
                "OFFLOAD_FS_PATH", os.path.join(self.db.root, "_offload"))
            tmp_frozen = target_dir + ".restore-frozen"
            shutil.rmtree(tmp_frozen, ignore_errors=True)
            try:
                os.makedirs(tmp_dir, exist_ok=True)
                for rel in entry["files"]:
                    inner = os.path.relpath(rel, cls)
                    if rel.startswith(frozen_prefix + os.sep):
                        # frozen-tenant files STAGE first — writing into
                        # the live offload tier mid-restore would corrupt
                        # an existing frozen copy if a later download fails
                        sub = os.path.relpath(rel, frozen_prefix)
                        dst = os.path.normpath(
                            os.path.join(tmp_frozen, sub))
                        confine(tmp_frozen, dst)
                    else:
                        # a tampered manifest must not escape tmp_dir
                        dst = os.path.normpath(os.path.join(tmp_dir, inner))
                        confine(tmp_dir, dst)
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    backend.get_file(backup_id, rel, dst)
                # all downloads succeeded. Pre-validate that every frozen
                # destination can be cleared BEFORE installing anything —
                # a mid-loop failure after some tenants moved would leave
                # a half-restored offload tier (no-partial-restores)
                frozen_moves = []
                if os.path.isdir(tmp_frozen):
                    dst_root = os.path.join(offload_base, cls)
                    os.makedirs(dst_root, exist_ok=True)
                    for tname in os.listdir(tmp_frozen):
                        tdst = os.path.join(dst_root, tname)
                        # graftlint: allow[unverified-remote-delete] reason=replacing a stale frozen copy with the just-downloaded backup payload; every file was fetched successfully above and the replacement is staged in tmp_frozen before this clear
                        shutil.rmtree(tdst, ignore_errors=True)
                        if os.path.exists(tdst):
                            # a surviving stale dir would make move() NEST
                            # the restore inside it — fail loudly, before
                            # any tenant has been installed
                            raise BackupError(
                                f"cannot clear stale frozen copy {tdst}")
                        frozen_moves.append((tname, tdst))
                # commit the hot dir first (atomic), then the frozen
                # tenants (destinations proven clear above; shutil.move
                # because the offload tier is commonly another mount)
                os.replace(tmp_dir, target_dir)
                for tname, tdst in frozen_moves:
                    shutil.move(os.path.join(tmp_frozen, tname), tdst)
                shutil.rmtree(tmp_frozen, ignore_errors=True)
                cfg = CollectionConfig.from_dict(entry["config"])
                col = self.db.create_collection(cfg)
                for tname, tstatus in entry.get("tenants", {}).items():
                    col.add_tenant(tname, tstatus)
                restored.append(cls)
            except (OSError, BackupError) as e:
                shutil.rmtree(tmp_dir, ignore_errors=True)
                shutil.rmtree(tmp_frozen, ignore_errors=True)
                if isinstance(e, BackupError):
                    raise
                raise BackupError(f"restore {cls!r} failed: {e}") from e
        return {"id": backup_id, "status": STATUS_SUCCESS,
                "classes": restored}
