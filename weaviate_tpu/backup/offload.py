"""FROZEN-tenant offload tier + usage reporting over object stores.

Reference: ``modules/offload-s3`` (FREEZING uploads tenant shard files to a
bucket, UNFREEZING downloads them back) and ``modules/usage-{s3,gcs}`` +
``cluster/usage`` (periodic usage reports written to a bucket). The local
filesystem tier stays the default (zero-egress); setting
``OFFLOAD_S3_BUCKET`` (reference's env) routes frozen tenants through the
S3 client instead.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

from weaviate_tpu.backup.object_store import (
    GCSClient,
    HttpFn,
    ObjectStoreClient,
    S3Client,
)

logger = logging.getLogger("weaviate_tpu.backup")


class ObjectStoreOffloader:
    """Move tenant shard directories to/from an object store under
    ``offload/<collection>/<tenant>/``."""

    def __init__(self, client: ObjectStoreClient):
        self.client = client

    def _prefix(self, collection: str, tenant: str) -> str:
        return f"offload/{collection}/{tenant}/"

    def upload(self, collection: str, tenant: str, shard_dir: str) -> int:
        pre = self._prefix(collection, tenant)
        # clear any previous frozen copy first: after unfreeze+compaction
        # the re-frozen file set shrinks, and stale segment keys left in
        # the bucket would resurrect deleted data on the next download
        # (the filesystem tier's rmtree-before-move invariant)
        for stale in self.client.list(pre):
            # graftlint: allow[unverified-remote-delete] reason=clearing the PREVIOUS frozen generation before re-upload; the local shard_dir being uploaded is the authoritative copy and still on disk, so nothing unrecoverable is deleted
            self.client.delete(stale)
        n = 0
        for dirpath, _dirs, files in os.walk(shard_dir):
            for fn in files:
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, shard_dir).replace(os.sep, "/")
                self.client.put_file(pre + rel, full)  # streamed
                n += 1
        return n

    def download(self, collection: str, tenant: str, shard_dir: str) -> int:
        pre = self._prefix(collection, tenant)
        n = 0
        for key in self.client.list(pre):
            rel = key[len(pre):]
            if not rel or rel.startswith("/") or ".." in rel.split("/"):
                continue  # hostile key names must not escape shard_dir
            dst = os.path.join(shard_dir, *rel.split("/"))
            if self.client.get_to_file(key, dst):
                n += 1
        return n

    def exists(self, collection: str, tenant: str) -> bool:
        return bool(self.client.list(self._prefix(collection, tenant)))


def get_offloader(http: Optional[HttpFn] = None
                  ) -> Optional[ObjectStoreOffloader]:
    """Env-gated (reference offload-s3 registers only when configured)."""
    bucket = os.environ.get("OFFLOAD_S3_BUCKET", "")
    if not bucket:
        return None
    return ObjectStoreOffloader(S3Client(
        bucket=bucket,
        region=os.environ.get("AWS_REGION", "us-east-1"),
        endpoint=os.environ.get("OFFLOAD_S3_ENDPOINT", ""),
        http=http))


class UsageReporter:
    """Periodic usage snapshots to a bucket (reference ``cluster/usage`` +
    ``modules/usage-{s3,gcs}``: per-node collection/shard/object counts
    written as JSON for billing/ops pipelines)."""

    def __init__(self, db, client: ObjectStoreClient, node: str = "node-0",
                 prefix: str = "usage"):
        self.db = db
        self.client = client
        self.node = node
        self.prefix = prefix
        self.reports = 0

    def build_report(self) -> dict:
        cols = {}
        for name in self.db.collections():
            try:
                c = self.db.get_collection(name)
                st = c.stats()
                cols[name] = {
                    "objects": st.get("objects"),
                    "shards": len(st.get("shards", {})),
                    "tenants": len(st.get("tenants", {})),
                }
            except Exception:
                # usage report is best-effort per collection, but a
                # collection that cannot be read should show up somewhere
                logger.warning("usage report skipped collection %s", name,
                               exc_info=True)
                continue
        return {"node": self.node, "ts": time.time(),
                "collections": cols}

    def report_once(self) -> str:
        rep = self.build_report()
        key = (f"{self.prefix}/{self.node}/"
               f"{time.strftime('%Y%m%dT%H%M%SZ', time.gmtime())}.json")
        self.client.put(key, json.dumps(rep).encode())
        self.reports += 1
        return key


def get_usage_reporter(db, http: Optional[HttpFn] = None
                       ) -> Optional[UsageReporter]:
    node = os.environ.get("CLUSTER_HOSTNAME", "node-0")
    s3b = os.environ.get("USAGE_S3_BUCKET", "")
    if s3b:
        return UsageReporter(db, S3Client(
            bucket=s3b, region=os.environ.get("AWS_REGION", "us-east-1"),
            endpoint=os.environ.get("USAGE_S3_ENDPOINT", ""), http=http),
            node=node)
    gcsb = os.environ.get("USAGE_GCS_BUCKET", "")
    if gcsb:
        return UsageReporter(db, GCSClient(
            bucket=gcsb,
            endpoint=os.environ.get("USAGE_GCS_ENDPOINT", ""), http=http),
            node=node)
    return None
