"""Use-case layer: operations composed over the DB (reference usecases/)."""
