"""Classification: fill property values from vector neighborhoods.

Reference: ``usecases/classification/`` — POST /v1/classifications starts a
background run that finds unlabeled objects (classifyProperties unset) and
writes predicted values:

- ``knn``: majority vote over the k nearest LABELED objects
  (``classifier_run_knn.go``)
- ``zeroshot``: nearest object in the TARGET class of a reference
  property; the winning target's uuid becomes the ref value
  (``classifier_run_zeroshot.go``)

TPU-first: the reference classifies object-by-object in worker goroutines;
here ALL unlabeled objects' vectors go to the device as one query batch —
classification is literally one batched vector search plus a host vote.
"""

from __future__ import annotations

import threading
import time
import uuid as uuidlib
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass
class Classification:
    id: str
    collection: str
    classify_properties: list[str]
    based_on_properties: list[str]  # informational (vectors drive knn)
    type: str = "knn"  # knn | zeroshot
    k: int = 3
    status: str = "running"  # running | completed | failed
    error: str = ""
    counts: dict = field(default_factory=lambda: {
        "count": 0, "successful": 0, "failed": 0})

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "class": self.collection,
            "classifyProperties": self.classify_properties,
            "basedOnProperties": self.based_on_properties,
            "type": self.type,
            "status": self.status,
            "error": self.error or None,
            "meta": dict(self.counts),
        }


class ClassificationManager:
    def __init__(self, db):
        self.db = db
        self._runs: dict[str, Classification] = {}
        self._lock = threading.Lock()

    def get(self, cid: str) -> Optional[Classification]:
        with self._lock:
            return self._runs.get(cid)

    def start(self, collection: str, classify_properties: list[str],
              based_on_properties: Optional[list[str]] = None,
              kind: str = "knn", k: int = 3,
              background: bool = False) -> Classification:
        if kind not in ("knn", "zeroshot"):
            raise ValueError(f"unknown classification type {kind!r}")
        col = self.db.get_collection(collection)  # raises on unknown class
        for p in classify_properties:
            if col.config.property(p) is None:
                raise ValueError(f"unknown classify property {p!r}")
        c = Classification(
            id=str(uuidlib.uuid4()), collection=collection,
            classify_properties=list(classify_properties),
            based_on_properties=list(based_on_properties or []),
            type=kind, k=k)
        with self._lock:
            self._runs[c.id] = c
        if background:
            threading.Thread(target=self._run, args=(c,), daemon=True).start()
        else:
            self._run(c)
        return c

    # -- the run -----------------------------------------------------------
    def _run(self, c: Classification) -> None:
        try:
            if c.type == "knn":
                self._run_knn(c)
            else:
                self._run_zeroshot(c)
            c.status = "completed"
        except Exception as e:  # surfaced in status, like the reference
            c.status = "failed"
            c.error = str(e)

    def _split_labeled(self, col, props: list[str]):
        labeled, unlabeled = [], []
        for shard in col._search_shards():
            for _k, raw in shard.objects.items():
                from weaviate_tpu.storage.objects import StorageObject

                o = StorageObject.from_bytes(raw)
                if o.vector is None:
                    continue
                if all(o.properties.get(p) is not None for p in props):
                    labeled.append(o)
                else:
                    unlabeled.append(o)
        return labeled, unlabeled

    def _run_knn(self, c: Classification) -> None:
        col = self.db.get_collection(c.collection)
        labeled, unlabeled = self._split_labeled(col, c.classify_properties)
        c.counts["count"] = len(unlabeled)
        if not unlabeled:
            return
        if not labeled:
            raise ValueError("no labeled objects to learn from")

        # the reference takes the k nearest LABELED objects — restrict the
        # search to labeled docs via per-shard allow masks (an over-fetch
        # heuristic would fail inside unlabeled clusters), still ONE device
        # batch per shard for every unlabeled object
        queries = np.stack([o.vector for o in unlabeled]).astype(np.float32)
        per_query: list[list[tuple[float, Any]]] = [[] for _ in unlabeled]
        for shard in col._search_shards():
            labeled_ids = set()
            for o in labeled:
                s = shard.get_by_uuid(o.uuid)
                if s is not None:
                    labeled_ids.add(s.doc_id)
            if not labeled_ids:
                continue
            space = max(shard._next_doc_id, 1)
            allow = np.zeros(space, bool)
            allow[list(labeled_ids)] = True
            res = shard.vector_search(queries, c.k, allow_list=allow)
            for qi in range(len(unlabeled)):
                for d, i in zip(res.dists[qi], res.ids[qi]):
                    if i >= 0:
                        obj = shard.get_by_docid(int(i))
                        if obj is not None:
                            per_query[qi].append((float(d), obj))
        updated = []
        for o, cands in zip(unlabeled, per_query):
            cands.sort(key=lambda t: t[0])
            votes: dict[str, Counter] = {p: Counter()
                                         for p in c.classify_properties}
            for _d, hit in cands[: c.k]:
                for p in c.classify_properties:
                    v = hit.properties.get(p)
                    if v is not None:
                        votes[p][_vote_key(v)] += 1
            ok = False
            for p in c.classify_properties:
                # fill only UNSET properties: a partially labeled object
                # lands in `unlabeled`, but its human-set values must not
                # be overwritten by the vote (the reference classifier
                # only writes nil properties)
                if votes[p] and o.properties.get(p) is None:
                    o.properties[p] = votes[p].most_common(1)[0][0]
                    ok = True
            if ok:
                updated.append(o)
                c.counts["successful"] += 1
            else:
                c.counts["failed"] += 1
        if updated:
            col.put_batch(updated)

    def _run_zeroshot(self, c: Classification) -> None:
        """Ref properties: point each unlabeled object at the nearest object
        of the property's target collection (no training data needed)."""
        col = self.db.get_collection(c.collection)
        labeled, unlabeled = self._split_labeled(col, c.classify_properties)
        c.counts["count"] = len(unlabeled)
        if not unlabeled:
            return
        queries = np.stack([o.vector for o in unlabeled]).astype(np.float32)
        assigned = [False] * len(unlabeled)
        for p in c.classify_properties:
            prop = col.config.property(p)
            target_cls = (prop.target_collection
                          if prop is not None else None)
            if not target_cls:
                raise ValueError(
                    f"zeroshot requires a reference property with a target "
                    f"collection; {p!r} has none")
            target = self.db.get_collection(target_cls)
            rows = target.vector_search_batch(queries, k=1)
            for qi, (o, row) in enumerate(zip(unlabeled, rows)):
                if row:
                    o.properties[p] = [{
                        "beacon":
                            f"weaviate://localhost/{target_cls}/{row[0][0].uuid}"
                    }]
                    assigned[qi] = True
        # counts are per OBJECT (meta.count is), not per (property, object)
        c.counts["successful"] = sum(assigned)
        c.counts["failed"] = len(unlabeled) - sum(assigned)
        col.put_batch(unlabeled)


def _vote_key(v: Any):
    if isinstance(v, list):
        return tuple(v)
    return v
