"""Classification: fill property values from vector neighborhoods.

Reference: ``usecases/classification/`` — POST /v1/classifications starts a
background run that finds unlabeled objects (classifyProperties unset) and
writes predicted values:

- ``knn``: majority vote over the k nearest LABELED objects
  (``classifier_run_knn.go``)
- ``zeroshot``: nearest object in the TARGET class of a reference
  property; the winning target's uuid becomes the ref value
  (``classifier_run_zeroshot.go``)
- ``contextual`` (reference ``text2vec-contextionary-contextual``,
  ``validation.go:24``): no training data; each source's basedOn TEXT is
  TF-IDF-matched against the target collection's texts — informative
  words dominate, mirroring the contextionary's IDF-boosted vector
  composition — and the winning target becomes the ref value.

TPU-first: the reference classifies object-by-object in worker goroutines;
here ALL unlabeled objects' vectors go to the device as one query batch —
classification is literally one batched vector search plus a host vote
(contextual scores are one dense [sources, vocab] @ [vocab, targets]
matmul on host numpy — BLAS, vocab-capped).
"""

from __future__ import annotations

import threading
import time
import uuid as uuidlib
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass
class Classification:
    id: str
    collection: str
    classify_properties: list[str]
    based_on_properties: list[str]  # informational (vectors drive knn)
    type: str = "knn"  # knn | zeroshot
    k: int = 3
    status: str = "running"  # running | completed | failed
    error: str = ""
    counts: dict = field(default_factory=lambda: {
        "count": 0, "successful": 0, "failed": 0})

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "class": self.collection,
            "classifyProperties": self.classify_properties,
            "basedOnProperties": self.based_on_properties,
            "type": self.type,
            "status": self.status,
            "error": self.error or None,
            "meta": dict(self.counts),
        }


class ClassificationManager:
    def __init__(self, db):
        self.db = db
        self._runs: dict[str, Classification] = {}
        self._lock = threading.Lock()

    def get(self, cid: str) -> Optional[Classification]:
        with self._lock:
            return self._runs.get(cid)

    def start(self, collection: str, classify_properties: list[str],
              based_on_properties: Optional[list[str]] = None,
              kind: str = "knn", k: int = 3,
              background: bool = False) -> Classification:
        if kind == "text2vec-contextionary-contextual":  # reference alias
            kind = "contextual"
        if kind not in ("knn", "zeroshot", "contextual"):
            raise ValueError(f"unknown classification type {kind!r}")
        if kind == "contextual" and not based_on_properties:
            # upfront like the reference validator (validation.go) — NOT in
            # the run, where a fully-labeled collection would short-circuit
            # to 'completed' before noticing the invalid request
            raise ValueError(
                "contextual classification requires basedOnProperties")
        col = self.db.get_collection(collection)  # raises on unknown class
        for p in classify_properties:
            if col.config.property(p) is None:
                raise ValueError(f"unknown classify property {p!r}")
        c = Classification(
            id=str(uuidlib.uuid4()), collection=collection,
            classify_properties=list(classify_properties),
            based_on_properties=list(based_on_properties or []),
            type=kind, k=k)
        with self._lock:
            self._runs[c.id] = c
        if background:
            threading.Thread(target=self._run, args=(c,), daemon=True).start()
        else:
            self._run(c)
        return c

    # -- the run -----------------------------------------------------------
    def _run(self, c: Classification) -> None:
        try:
            if c.type == "knn":
                self._run_knn(c)
            elif c.type == "contextual":
                self._run_contextual(c)
            else:
                self._run_zeroshot(c)
            c.status = "completed"
        except Exception as e:  # surfaced in status, like the reference
            c.status = "failed"
            c.error = str(e)

    def _split_labeled(self, col, props: list[str]):
        labeled, unlabeled = [], []
        for shard in col._search_shards():
            for _k, raw in shard.objects.items():
                from weaviate_tpu.storage.objects import StorageObject

                o = StorageObject.from_bytes(raw)
                if o.vector is None:
                    continue
                if all(o.properties.get(p) is not None for p in props):
                    labeled.append(o)
                else:
                    unlabeled.append(o)
        return labeled, unlabeled

    def _run_knn(self, c: Classification) -> None:
        col = self.db.get_collection(c.collection)
        labeled, unlabeled = self._split_labeled(col, c.classify_properties)
        c.counts["count"] = len(unlabeled)
        if not unlabeled:
            return
        if not labeled:
            raise ValueError("no labeled objects to learn from")

        # the reference takes the k nearest LABELED objects — restrict the
        # search to labeled docs via per-shard allow masks (an over-fetch
        # heuristic would fail inside unlabeled clusters), still ONE device
        # batch per shard for every unlabeled object
        queries = np.stack([o.vector for o in unlabeled]).astype(np.float32)
        per_query: list[list[tuple[float, Any]]] = [[] for _ in unlabeled]
        for shard in col._search_shards():
            labeled_ids = set()
            for o in labeled:
                s = shard.get_by_uuid(o.uuid)
                if s is not None:
                    labeled_ids.add(s.doc_id)
            if not labeled_ids:
                continue
            space = max(shard._next_doc_id, 1)
            allow = np.zeros(space, bool)
            allow[list(labeled_ids)] = True
            res = shard.vector_search(queries, c.k, allow_list=allow)
            for qi in range(len(unlabeled)):
                for d, i in zip(res.dists[qi], res.ids[qi]):
                    if i >= 0:
                        obj = shard.get_by_docid(int(i))
                        if obj is not None:
                            per_query[qi].append((float(d), obj))
        updated = []
        for o, cands in zip(unlabeled, per_query):
            cands.sort(key=lambda t: t[0])
            votes: dict[str, Counter] = {p: Counter()
                                         for p in c.classify_properties}
            for _d, hit in cands[: c.k]:
                for p in c.classify_properties:
                    v = hit.properties.get(p)
                    if v is not None:
                        votes[p][_vote_key(v)] += 1
            ok = False
            for p in c.classify_properties:
                # fill only UNSET properties: a partially labeled object
                # lands in `unlabeled`, but its human-set values must not
                # be overwritten by the vote (the reference classifier
                # only writes nil properties)
                if votes[p] and o.properties.get(p) is None:
                    o.properties[p] = votes[p].most_common(1)[0][0]
                    ok = True
            if ok:
                updated.append(o)
                c.counts["successful"] += 1
            else:
                c.counts["failed"] += 1
        if updated:
            col.put_batch(updated)

    def _run_zeroshot(self, c: Classification) -> None:
        """Ref properties: point each unlabeled object at the nearest object
        of the property's target collection (no training data needed)."""
        col = self.db.get_collection(c.collection)
        labeled, unlabeled = self._split_labeled(col, c.classify_properties)
        c.counts["count"] = len(unlabeled)
        if not unlabeled:
            return
        queries = np.stack([o.vector for o in unlabeled]).astype(np.float32)
        assigned = [False] * len(unlabeled)
        for p in c.classify_properties:
            prop = col.config.property(p)
            target_cls = (prop.target_collection
                          if prop is not None else None)
            if not target_cls:
                raise ValueError(
                    f"zeroshot requires a reference property with a target "
                    f"collection; {p!r} has none")
            target = self.db.get_collection(target_cls)
            rows = target.vector_search_batch(queries, k=1)
            for qi, (o, row) in enumerate(zip(unlabeled, rows)):
                if row:
                    o.properties[p] = [{
                        "beacon":
                            f"weaviate://localhost/{target_cls}/{row[0][0].uuid}"
                    }]
                    assigned[qi] = True
        # counts are per OBJECT (meta.count is), not per (property, object)
        c.counts["successful"] = sum(assigned)
        c.counts["failed"] = len(unlabeled) - sum(assigned)
        col.put_batch(unlabeled)


    def _run_contextual(self, c: Classification) -> None:
        """Training-data-free ref classification by TF-IDF text relevance
        (reference contextual type): score every (source, target) pair as
        the cosine of their IDF-weighted term vectors over the TARGET
        corpus's vocabulary, assign the argmax target's beacon."""
        from weaviate_tpu.inverted.analyzer import term_frequencies

        from weaviate_tpu.schema.config import DataType as _DT

        col = self.db.get_collection(c.collection)
        _, unlabeled = self._split_labeled(col, c.classify_properties)
        c.counts["count"] = len(unlabeled)
        if not unlabeled:
            return

        def text_of(o, props):
            out = []
            for p in props:
                v = o.properties.get(p)
                if isinstance(v, str):
                    out.append(v)
                elif isinstance(v, list):
                    out.extend(x for x in v if isinstance(x, str))
            return " ".join(out)

        # source term frequencies depend only on basedOn text: compute once
        src_tfs = [term_frequencies(
            text_of(o, c.based_on_properties), "word", set())
            for o in unlabeled]
        assigned = [False] * len(unlabeled)
        for p in c.classify_properties:
            prop = col.config.property(p)
            target_cls = prop.target_collection if prop is not None else None
            if not target_cls:
                raise ValueError(
                    f"contextual requires a reference property with a "
                    f"target collection; {p!r} has none")
            target = self.db.get_collection(target_cls)
            t_objs, t_tfs = [], []
            # TEXT props only: str() of refs/numbers would pollute the
            # vocabulary with beacon fragments and digit tokens
            text_props = [q.name for q in target.config.properties
                          if q.data_type in (_DT.TEXT, _DT.TEXT_ARRAY)]
            for shard in target._search_shards():
                from weaviate_tpu.storage.objects import StorageObject

                for _k, raw in shard.objects.items():
                    o = StorageObject.from_bytes(raw)
                    t_objs.append(o)
                    t_tfs.append(term_frequencies(
                        text_of(o, text_props), "word", set()))
            if not t_objs:
                raise ValueError(f"target collection {target_cls} is empty")
            # vocabulary + idf over the TARGET corpus (informative words
            # dominate, rare-everywhere words contribute little)
            df: Counter = Counter()
            for tf in t_tfs:
                df.update(tf.keys())
            n_t = len(t_objs)
            # cap the vocabulary by keeping the most INFORMATIVE terms
            # (lowest df — ubiquitous words carry no signal and their IDF
            # is ~0 anyway); ties broken deterministically by term
            if len(df) > 20_000:
                vocab = [w for w, _n in sorted(
                    df.items(), key=lambda t: (t[1], t[0]))[:20_000]]
            else:
                vocab = list(df)
            vix = {w: i for i, w in enumerate(vocab)}
            idf = np.log(1.0 + n_t / (1.0 + np.asarray(
                [df[w] for w in vocab], np.float32)))

            def tfidf(tf: dict) -> np.ndarray:
                v = np.zeros(len(vocab), np.float32)
                for w, n in tf.items():
                    i = vix.get(w)
                    if i is not None:
                        v[i] = n
                v *= idf
                norm = np.linalg.norm(v)
                return v / norm if norm > 0 else v

            tmat = np.stack([tfidf(tf) for tf in t_tfs])        # [T, V]
            smat = np.stack([tfidf(tf) for tf in src_tfs])      # [S, V]
            scores = smat @ tmat.T                              # [S, T]
            best = np.argmax(scores, axis=1)
            for qi, o in enumerate(unlabeled):
                if scores[qi, best[qi]] <= 0:
                    continue  # no textual overlap: leave unassigned
                o.properties[p] = [{
                    "beacon": "weaviate://localhost/"
                    f"{target_cls}/{t_objs[best[qi]].uuid}"}]
                assigned[qi] = True
        c.counts["successful"] = sum(assigned)
        c.counts["failed"] = len(unlabeled) - sum(assigned)
        col.put_batch([o for ok, o in zip(assigned, unlabeled) if ok])


def _vote_key(v: Any):
    if isinstance(v, list):
        return tuple(v)
    return v
