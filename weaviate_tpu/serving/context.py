"""Per-request serving context: the end-to-end deadline, thread-scoped.

One :class:`~weaviate_tpu.cluster.resilience.Deadline` is minted at
ingress (REST ``X-Request-Timeout`` header / gRPC context deadline /
server default) and travels with the request. Deep layers — collection
scatter-gather, the coalescing dispatcher, the cluster replica fan-out —
read it from here instead of growing a ``deadline=`` parameter on every
signature in between.

Scope is THREAD-local, not a contextvar: the query engine fans work out
through plain ``ThreadPoolExecutor`` pools, which never propagate
contextvars. Any closure that hops threads re-enters the scope explicitly
(``with request_scope(ctx):`` — see ``Collection.vector_search_batch``),
which keeps the propagation points grep-able.

This module depends on nothing but the stdlib so every layer may import
it without cycles; the Deadline object itself is duck-typed (anything
with ``remaining()/expired/require()``).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Iterator, Optional


@dataclass
class RequestContext:
    """What the QoS layer learned about one in-flight request."""

    deadline: Optional[Any] = None  # cluster.resilience.Deadline
    lane: str = ""
    tenant: str = ""
    queue_wait_s: float = 0.0  # admission-queue wait, for slow-query logs
    # the ingress span (monitoring.tracing.Span) minted with the deadline:
    # re-entering the scope in a pool thread re-activates it there, so
    # spans created deep in scatter/dispatch work parent to the request's
    # trace instead of starting disconnected roots
    trace: Optional[Any] = None


_local = threading.local()


def current() -> Optional[RequestContext]:
    return getattr(_local, "ctx", None)


def current_deadline() -> Optional[Any]:
    ctx = current()
    return None if ctx is None else ctx.deadline


@contextmanager
def request_scope(ctx: Optional[RequestContext]) -> Iterator[
        Optional[RequestContext]]:
    """Install ``ctx`` as the thread's request context; restores the
    previous one on exit so nested scopes (a subrequest minting a shorter
    deadline) unwind correctly."""
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    token = None
    span = getattr(ctx, "trace", None)
    if span is not None:
        # lazy: tracing is stdlib-only but keeping this module's import
        # graph empty until a trace actually rides a context
        from weaviate_tpu.monitoring import tracing

        token = tracing.activate(span)
    try:
        yield ctx
    finally:
        if token is not None:
            from weaviate_tpu.monitoring import tracing

            tracing.deactivate(token)
        _local.ctx = prev
