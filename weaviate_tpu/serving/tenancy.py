"""Per-tenant rate limiting: token buckets with per-tenant overrides.

Multi-tenancy is a first-class axis in the reference (§2.2) and the
fairness failure mode is always the same: one hot tenant saturates the
shared admission queue and every other tenant's p99 rides along. The
throttle answers *before* a request may even enter the queue; the
weighted-fair dequeue in :mod:`~weaviate_tpu.serving.qos` handles the
tenants that got in.

``rate <= 0`` disables the default bucket (unlimited), so single-tenant
deployments pay nothing; per-tenant overrides still apply.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second, ``burst`` capacity.

    ``try_take`` returns 0.0 on admission, else the seconds until the
    requested tokens will exist — the client's Retry-After.
    """

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            if self.rate <= 0:
                return 60.0  # bucket can never refill; long back-off
            return (n - self._tokens) / self.rate


class TenantThrottle:
    """tenant -> TokenBucket registry with lazy creation.

    ``default_rate``/``default_burst`` govern tenants without an explicit
    override; a default rate <= 0 means unthrottled (no bucket is even
    created). ``set_limit`` pins a specific tenant's budget — rate <= 0
    there means that tenant is explicitly unlimited.
    """

    # hard cap on tracked buckets: the tenant string is CLIENT-controlled
    # (X-Tenant header / ?tenant=), so the registry itself must be bounded
    # or the throttle becomes the memory-overload vector it guards against
    MAX_TRACKED = 8192

    def __init__(self, default_rate: float = 0.0,
                 default_burst: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.default_rate = float(default_rate)
        self.default_burst = float(default_burst)
        self._clock = clock
        self._buckets: dict[str, TokenBucket] = {}
        self._overrides: dict[str, tuple[float, float]] = {}
        self._lock = threading.Lock()
        # tiering activity tap (tiering/controller.py on_tenant_signal):
        # the throttle sees every tenant-tagged request at the front door,
        # so it doubles as the serving-side activity feed — wired by the
        # DB when a tiering controller exists, else a no-op
        self.on_activity: Optional[Callable[[str], None]] = None

    def set_limit(self, tenant: str, rate: float, burst: float) -> None:
        with self._lock:
            self._overrides[tenant] = (float(rate), float(burst))
            self._buckets.pop(tenant, None)  # rebuild with new params

    def has_override(self, tenant: str) -> bool:
        """Operator explicitly pinned this tenant's budget — a BOUNDED
        set, safe to use as a metric label (arbitrary client-sent tenant
        strings are not)."""
        with self._lock:
            return tenant in self._overrides

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is not None:
                return bucket
            rate, burst = self._overrides.get(
                tenant, (self.default_rate, self.default_burst))
            if rate <= 0:
                return None  # unthrottled: never cache (unbounded names)
            if len(self._buckets) >= self.MAX_TRACKED:
                # evict the oldest-inserted tracked bucket (dict order);
                # it re-materializes full on next use — briefly generous
                # to one tenant beats unbounded growth
                self._buckets.pop(next(iter(self._buckets)))
            bucket = TokenBucket(rate, burst, clock=self._clock)
            self._buckets[tenant] = bucket
            return bucket

    def check(self, tenant: str) -> Optional[float]:
        """None = admitted; else seconds the tenant should wait."""
        if tenant and self.on_activity is not None:
            self.on_activity(tenant)
        bucket = self._bucket(tenant)
        if bucket is None:
            return None
        wait = bucket.try_take()
        return None if wait <= 0 else wait
