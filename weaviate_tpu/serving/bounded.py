"""Bounded-concurrency WSGI server for the REST plane.

werkzeug's ``make_server(threaded=True)`` is thread-per-connection with
no cap: 10k slow clients are 10k handler threads, and a client that
stops reading pins its thread forever (no socket timeout). This server
keeps werkzeug's request handling but:

- runs handlers on a FIXED pool (``max_handlers`` workers, sized from
  the admission controller's limiter by the caller);
- bounds accepted-but-unprocessed connections with a semaphore — when
  every worker is busy and the runway is full, the ACCEPT LOOP blocks,
  so overflow lands in the kernel listen backlog where the OS applies
  backpressure (instead of an unbounded in-process queue);
- sets a per-connection socket timeout so a slow-loris client gets
  disconnected instead of holding a worker hostage.

Load-based rejection (429) is the admission controller's job; this layer
only guarantees the PROCESS can't be resource-exhausted by connection
count alone.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

from werkzeug.serving import ThreadedWSGIServer


class BoundedThreadedWSGIServer(ThreadedWSGIServer):
    # runway beyond the worker count: connections parked here are cheap
    # (one fd + one semaphore token), and the admission controller sheds
    # their requests quickly once a worker picks them up
    RUNWAY_FACTOR = 2

    def __init__(self, host: str, port: int, app,
                 max_handlers: int = 32, read_timeout: float = 30.0):
        super().__init__(host, port, app)
        self.max_handlers = max(1, int(max_handlers))
        self.read_timeout = float(read_timeout)
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_handlers, thread_name_prefix="rest-handler")
        self._slots = threading.BoundedSemaphore(
            self.max_handlers * self.RUNWAY_FACTOR)

    def process_request(self, request, client_address):
        if self.read_timeout > 0:
            request.settimeout(self.read_timeout)
        # full runway blocks the accept loop (kernel-backlog
        # backpressure) — but never past a shutdown() request, which
        # the serve_forever loop can only honor once we return
        while not self._slots.acquire(timeout=0.5):
            if getattr(self, "_BaseServer__shutdown_request", False):
                self.shutdown_request(request)
                return
        try:
            self._pool.submit(self._run_one, request, client_address)
        except RuntimeError:  # pool already shut down mid-stop
            self._slots.release()
            self.shutdown_request(request)

    def _run_one(self, request, client_address):
        try:
            # ThreadingMixIn's worker body: finish_request + handle_error
            # + shutdown_request, exactly what the unbounded server ran
            self.process_request_thread(request, client_address)
        finally:
            self._slots.release()

    def server_close(self):
        super().server_close()
        self._pool.shutdown(wait=False)
