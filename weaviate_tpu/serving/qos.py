"""Admission controller: bounded lanes, AIMD ceiling, explicit shedding.

The front door's overload contract (ISSUE 4; the shape every TPU
inference server needs between its RPC plane and its batch scheduler):

- Work is classified into LANES — ``interactive`` (search), ``batch``
  (bulk ingest), ``background`` (schema/ops) — each with its own bounded
  queue and a weight for the fair dequeue. A full lane sheds instead of
  queueing: HTTP 429 / gRPC RESOURCE_EXHAUSTED with a computed
  ``Retry-After``, never an invisible unbounded queue.
- Total in-flight work is capped by an :class:`AIMDLimiter` ceiling fed
  with observed queue+execute latency, so the cap tracks what the
  hardware can actually sustain instead of a hand-tuned constant.
- A request whose :class:`~weaviate_tpu.cluster.resilience.Deadline` is
  already spent (or expires while queued) is shed with 504 /
  DEADLINE_EXCEEDED *here*, before it can burn a device batch slot.
- Dequeue is weighted-fair: smooth weighted round-robin across lanes
  (nginx's algorithm), plain round-robin across tenants inside a lane —
  one hot tenant cannot starve the rest even after the token bucket
  (:mod:`~weaviate_tpu.serving.tenancy`) let its requests in.

The whole layer is bypassable at runtime: ``serving_qos=off`` in the
runtime-overrides file restores the pre-QoS behavior (every acquire
returns a no-op ticket).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from weaviate_tpu.monitoring.metrics import (
    QOS_ADMITTED,
    QOS_EXPIRED,
    QOS_INFLIGHT,
    QOS_QUEUE_DEPTH,
    QOS_QUEUE_WAIT,
    QOS_SHED,
    QOS_TENANT_THROTTLED,
)
from weaviate_tpu.serving.limiter import AIMDLimiter
from weaviate_tpu.serving.tenancy import TenantThrottle

INTERACTIVE = "interactive"
BATCH = "batch"
BACKGROUND = "background"


class QosRejected(RuntimeError):
    """Load shed: the caller should retry after ``retry_after`` seconds
    (HTTP 429 + Retry-After / gRPC RESOURCE_EXHAUSTED)."""

    def __init__(self, message: str, retry_after: float, reason: str):
        super().__init__(message)
        self.retry_after = retry_after
        self.reason = reason


@dataclass(frozen=True)
class LaneConfig:
    name: str
    weight: int  # fair-dequeue share relative to the other lanes
    max_queue_depth: int  # waiters beyond this are shed, not queued


DEFAULT_LANES = (
    LaneConfig(INTERACTIVE, weight=8, max_queue_depth=64),
    LaneConfig(BATCH, weight=2, max_queue_depth=32),
    LaneConfig(BACKGROUND, weight=1, max_queue_depth=32),
)


class _Waiter:
    __slots__ = ("lane", "tenant", "event", "admitted")

    def __init__(self, lane: str, tenant: str):
        self.lane = lane
        self.tenant = tenant
        self.event = threading.Event()
        self.admitted = False


class _Ticket:
    """Held for the request's execution; releasing it feeds the limiter
    and hands the freed slot to the next fair-dequeue winner."""

    __slots__ = ("_ctl", "lane", "t0", "queue_wait")

    def __init__(self, ctl: Optional["AdmissionController"], lane: str,
                 t0: float, queue_wait: float = 0.0):
        self._ctl = ctl  # None = QoS bypassed, ticket is a no-op
        self.lane = lane
        self.t0 = t0
        self.queue_wait = queue_wait

    def __enter__(self) -> "_Ticket":
        return self

    def __exit__(self, *exc) -> bool:
        if self._ctl is not None:
            self._ctl._release(self)
        return False


class AdmissionController:
    def __init__(self, limiter: Optional[AIMDLimiter] = None,
                 throttle: Optional[TenantThrottle] = None,
                 lanes: tuple[LaneConfig, ...] = DEFAULT_LANES,
                 clock: Callable[[], float] = time.monotonic):
        self.limiter = limiter or AIMDLimiter()
        self.throttle = throttle or TenantThrottle()
        self.lanes = {cfg.name: cfg for cfg in lanes}
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = 0
        # lane -> tenant -> waiter FIFO; depth is enforced under _lock
        # before every append, so these can never grow past the lane cap
        self._queues: dict[str, dict[str, deque]] = {
            name: {} for name in self.lanes}
        self._depths: dict[str, int] = {name: 0 for name in self.lanes}
        self._tenant_ring: dict[str, list[str]] = {
            name: [] for name in self.lanes}
        self._credits: dict[str, float] = {name: 0.0 for name in self.lanes}
        self._svc_ewma = 0.05  # smoothed queue+execute seconds, Retry-After
        # ingest backpressure supplier (docs/ingest.md), wired by the DB:
        # () -> (pending vectors in the WAL->device window, compaction
        # debt bytes). When either crosses its runtime knob the BATCH
        # lane sheds with Retry-After — admission is where the pipeline
        # says "stop feeding me", before the WAL grows unbounded.
        self.ingest_pressure: Optional[Callable[[], tuple]] = None
        # per-lane admit/shed tallies feeding serving_stats(): raw
        # monotonic counts under their own lock (the shed paths raise
        # before _lock is ever taken), smoothed into a shed-fraction
        # EWMA per read so gossip ships a stable signal, not one
        # polling interval's noise
        self._sig_lock = threading.Lock()
        self._sig_counts: dict[str, list[int]] = {}  # lane -> [ok, shed]
        self._sig_prev: dict[str, tuple[int, int]] = {}
        self._shed_ewma: dict[str, float] = {}
        self._sig_ts: Optional[float] = None

    # -- admission ---------------------------------------------------------
    @staticmethod
    def enabled() -> bool:
        from weaviate_tpu.utils.runtime_config import SERVING_QOS

        return str(SERVING_QOS.get()).lower() not in ("off", "false", "0")

    def acquire(self, lane: str = INTERACTIVE, tenant: str = "",
                deadline=None) -> _Ticket:
        """Admit, queue, or shed. Returns a ticket (context manager) on
        admission; raises :class:`QosRejected` on shed and
        ``DeadlineExceeded`` when the request's budget is spent before a
        slot opened."""
        if not self.enabled():
            return _Ticket(None, lane, self._clock())
        cfg = self.lanes.get(lane) or self.lanes[BACKGROUND]
        lane = cfg.name
        if deadline is not None and deadline.expired:
            QOS_EXPIRED.inc(lane=lane)
            deadline.require()  # raises DeadlineExceeded
        if lane == BATCH:
            shed = self._check_ingest_pressure()
            if shed is not None:
                reason, retry_after = shed
                QOS_SHED.inc(lane=lane, reason=reason)
                self._note(lane, shed=True)
                raise QosRejected(
                    f"ingest backpressure: {reason.replace('_', ' ')} over "
                    "its shed threshold (the WAL->device window or merge "
                    "debt must drain first)",
                    retry_after=retry_after, reason=reason)
        throttle_wait = self.throttle.check(tenant)
        if throttle_wait is not None:
            # label cardinality must stay bounded: only operator-pinned
            # tenant names become series; the client-controlled rest
            # aggregate under "default"
            QOS_TENANT_THROTTLED.inc(
                tenant=tenant if self.throttle.has_override(tenant)
                else "default")
            QOS_SHED.inc(lane=lane, reason="tenant_rate")
            self._note(lane, shed=True)
            raise QosRejected(
                f"tenant {tenant or 'default'!r} over its rate limit",
                retry_after=max(1.0, math.ceil(throttle_wait)),
                reason="tenant_rate")
        t0 = self._clock()
        with self._lock:
            if self._inflight < self.limiter.ceiling \
                    and not self._queued_total():
                self._inflight += 1
                QOS_INFLIGHT.set(self._inflight)
                QOS_ADMITTED.inc(lane=lane)
                self._note(lane, shed=False)
                return _Ticket(self, lane, t0)
            if self._lane_depth(lane) >= cfg.max_queue_depth:
                QOS_SHED.inc(lane=lane, reason="queue_full")
                self._note(lane, shed=True)
                raise QosRejected(
                    f"overloaded: {lane} admission queue full "
                    f"(depth {cfg.max_queue_depth})",
                    retry_after=self._retry_after_locked(),
                    reason="queue_full")
            waiter = _Waiter(lane, tenant)
            self._enqueue_locked(waiter)
        try:
            self._wait(waiter, deadline)
        except BaseException:
            # not admitted (deadline/interrupt): leave no orphan waiter
            with self._lock:
                if not waiter.admitted:
                    self._remove_locked(waiter)
                admitted_anyway = waiter.admitted
            if admitted_anyway:
                # the slot was granted in the race window; hand it back
                self._release(_Ticket(self, lane, t0))
            raise
        queue_wait = self._clock() - t0
        # exemplar: the trace id of the worst queue wait per lane — the
        # jump-off point from the histogram to a concrete trace tree
        from weaviate_tpu.monitoring.tracing import current_trace_id

        QOS_QUEUE_WAIT.observe(queue_wait, lane=lane,
                               exemplar=current_trace_id())
        QOS_ADMITTED.inc(lane=lane)
        self._note(lane, shed=False)
        return _Ticket(self, lane, t0, queue_wait=queue_wait)

    def _check_ingest_pressure(self) -> Optional[tuple[str, float]]:
        """(reason, retry_after) when the ingest pipeline is over a shed
        threshold, else None. A knob set to 0 disables that signal. The
        Retry-After hint scales with how far past the threshold the
        signal is — at 3x the threshold a client backs off 3x longer
        (capped) than one arriving right at the line."""
        if self.ingest_pressure is None:
            return None
        from weaviate_tpu.utils.runtime_config import (
            INGEST_SHED_DEBT_BYTES,
            INGEST_SHED_QUEUE_DEPTH,
        )

        depth, debt = self.ingest_pressure()
        max_depth = int(INGEST_SHED_QUEUE_DEPTH.get())
        if max_depth > 0 and depth >= max_depth:
            return "ingest_queue", float(
                min(30.0, max(1.0, math.ceil(depth / max_depth))))
        max_debt = int(INGEST_SHED_DEBT_BYTES.get())
        if max_debt > 0 and debt >= max_debt:
            return "compaction_debt", float(
                min(30.0, max(1.0, math.ceil(debt / max_debt))))
        return None

    def _wait(self, waiter: _Waiter, deadline) -> None:
        while True:
            timeout = 5.0
            if deadline is not None:
                timeout = min(timeout, max(0.0, deadline.remaining()))
            if waiter.event.wait(timeout=timeout):
                return
            if deadline is not None and deadline.expired:
                QOS_EXPIRED.inc(lane=waiter.lane)
                deadline.require()  # raises DeadlineExceeded

    # -- release + fair dequeue --------------------------------------------
    def _release(self, ticket: _Ticket) -> None:
        total = max(0.0, self._clock() - ticket.t0)
        with self._lock:
            self._inflight -= 1
            self._svc_ewma = 0.8 * self._svc_ewma + 0.2 * max(total, 1e-4)
            self.limiter.record(total)
            while self._inflight < self.limiter.ceiling:
                waiter = self._pick_next_locked()
                if waiter is None:
                    break
                self._inflight += 1
                waiter.admitted = True
                waiter.event.set()
            QOS_INFLIGHT.set(self._inflight)

    def _pick_next_locked(self) -> Optional[_Waiter]:
        """Smooth weighted round-robin across non-empty lanes, then
        round-robin across that lane's tenants."""
        candidates = [name for name in self.lanes
                      if self._lane_depth(name) > 0]
        if not candidates:
            return None
        total_weight = sum(self.lanes[n].weight for n in candidates)
        for name in candidates:
            self._credits[name] += self.lanes[name].weight
        winner = max(candidates, key=lambda n: self._credits[n])
        self._credits[winner] -= total_weight
        ring = self._tenant_ring[winner]
        tenant = ring.pop(0)
        q = self._queues[winner][tenant]
        waiter = q.popleft()
        self._depths[winner] -= 1
        if q:
            ring.append(tenant)  # back of the ring: round-robin
        else:
            del self._queues[winner][tenant]
        QOS_QUEUE_DEPTH.set(self._depths[winner], lane=winner)
        return waiter

    # -- queue bookkeeping (all under _lock) -------------------------------
    def _enqueue_locked(self, waiter: _Waiter) -> None:
        by_tenant = self._queues[waiter.lane]
        if waiter.tenant not in by_tenant:
            by_tenant[waiter.tenant] = deque()  # graftlint: allow[unbounded-queue] reason=depth checked against max_queue_depth under _lock before every append
            self._tenant_ring[waiter.lane].append(waiter.tenant)
        by_tenant[waiter.tenant].append(waiter)
        self._depths[waiter.lane] += 1
        QOS_QUEUE_DEPTH.set(self._depths[waiter.lane], lane=waiter.lane)

    def _remove_locked(self, waiter: _Waiter) -> None:
        by_tenant = self._queues[waiter.lane]
        q = by_tenant.get(waiter.tenant)
        if q is None:
            return
        try:
            q.remove(waiter)
        except ValueError:
            return  # already dequeued by a releaser
        self._depths[waiter.lane] -= 1
        if not q:
            del by_tenant[waiter.tenant]
            try:
                self._tenant_ring[waiter.lane].remove(waiter.tenant)
            except ValueError:
                pass
        QOS_QUEUE_DEPTH.set(self._depths[waiter.lane], lane=waiter.lane)

    def _lane_depth(self, lane: str) -> int:
        # O(1) counter (kept in lock-step by enqueue/remove/pick): the
        # admission fast path reads this under the one global lock, so a
        # scan over tenants would make that lock hottest under overload
        return self._depths[lane]

    def _queued_total(self) -> int:
        return sum(self._depths.values())

    def _retry_after_locked(self) -> float:
        """Seconds until the backlog in front of a new arrival should have
        drained at the current service rate: depth x EWMA / ceiling."""
        backlog = self._queued_total() + self._inflight
        est = backlog * self._svc_ewma / max(1, self.limiter.ceiling)
        return float(min(60.0, max(1.0, math.ceil(est))))

    # -- serving signals (gossiped to the autoscaler) ----------------------
    def _note(self, lane: str, shed: bool) -> None:
        with self._sig_lock:
            c = self._sig_counts.setdefault(lane, [0, 0])
            c[1 if shed else 0] += 1

    def serving_stats(self) -> dict:
        """This node's serving-pressure advert: per-lane shed-fraction
        EWMAs plus the limiter's smoothed p99 vs its target. Rides the
        gossip node-meta payload (cluster/node.py ``_capacity_meta``) so
        the autoscale leader sees every node's pressure, not its own.
        Each call folds the admit/shed deltas since the previous call
        into a time-aware EWMA (tau ~5s) — a quiet window decays the
        fraction toward zero instead of freezing the last burst."""
        with self._sig_lock:
            now = self._clock()
            dt = (now - self._sig_ts) if self._sig_ts is not None else 1.0
            self._sig_ts = now
            alpha = 1.0 - math.exp(-max(dt, 1e-3) / 5.0)
            shed_rate: dict[str, float] = {}
            for lane in self.lanes:
                ok, shed = self._sig_counts.get(lane, [0, 0])
                pok, pshed = self._sig_prev.get(lane, (0, 0))
                self._sig_prev[lane] = (ok, shed)
                d_ok, d_shed = ok - pok, shed - pshed
                total = d_ok + d_shed
                frac = (d_shed / total) if total else 0.0
                prev = self._shed_ewma.get(lane, 0.0)
                cur = (1.0 - alpha) * prev + alpha * frac
                self._shed_ewma[lane] = cur
                shed_rate[lane] = round(cur, 4)
        return {
            "shed_rate": shed_rate,
            "p99_ewma_ms": round(self.limiter.p99_ewma * 1e3, 3),
            "p99_target_ms": round(self.limiter.target_p99_s * 1e3, 3),
        }

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "ceiling": self.limiter.ceiling,
                "inflight": self._inflight,
                "queued": {name: self._lane_depth(name)
                           for name in self._queues},
            }
