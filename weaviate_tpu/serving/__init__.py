"""Serving QoS layer: admission control, deadlines, adaptive shedding.

The layer between the API servers (REST/gRPC) and the query engine.
Reference analogue: the Go stack leans on gRPC's deadline machinery plus
goroutine-per-request cheapness; a TPU inference server cannot — device
batches are the throughput mechanism (SURVEY §7), so overload must be
absorbed BEFORE a request burns a batch slot. Three parts:

- :mod:`~weaviate_tpu.serving.qos` — admission controller: per-lane
  bounded queues (interactive / batch / background), an AIMD concurrency
  limiter driven by observed latency, explicit load shedding with a
  computed Retry-After, and weighted-fair dequeue across lanes+tenants.
- :mod:`~weaviate_tpu.serving.context` — per-request scope carrying the
  single end-to-end :class:`~weaviate_tpu.cluster.resilience.Deadline`
  from ingress through collection search, the coalescing dispatcher, and
  the cluster replica fan-out.
- :mod:`~weaviate_tpu.serving.bounded` — the bounded-concurrency WSGI
  server the REST plane runs on (thread-per-connection is how p99 dies).
"""

from weaviate_tpu.serving.context import (
    RequestContext,
    current,
    current_deadline,
    request_scope,
)
from weaviate_tpu.serving.limiter import AIMDLimiter
from weaviate_tpu.serving.qos import (
    AdmissionController,
    LaneConfig,
    QosRejected,
)
from weaviate_tpu.serving.tenancy import TenantThrottle, TokenBucket

__all__ = [
    "AdmissionController", "LaneConfig", "QosRejected", "AIMDLimiter",
    "TenantThrottle", "TokenBucket", "RequestContext", "request_scope",
    "current", "current_deadline",
]
