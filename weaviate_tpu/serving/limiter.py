"""AIMD concurrency limiter: the admission controller's adaptive ceiling.

Reference model: TCP congestion control applied to server concurrency
(the Netflix concurrency-limits shape). The controller feeds every
admitted request's END-TO-END latency (queue wait + execute) in; once a
window of samples has accumulated, the observed p99 is compared against
the target:

- p99 over target  -> multiplicative decrease (the server is past its
  latency knee; shrinking concurrency is the only move that helps)
- p99 under target -> additive increase (probe for headroom, one slot
  per window, so recovery is gradual and cannot oscillate wildly)

The ceiling is what the admission controller compares in-flight work
against; everything above it queues or sheds. Deterministic and fully
injectable — tests drive it by recording synthetic latencies.
"""

from __future__ import annotations

import threading

from weaviate_tpu.monitoring.metrics import QOS_LIMIT


class AIMDLimiter:
    def __init__(self, initial: int = 16, min_limit: int = 1,
                 max_limit: int = 256, target_p99_s: float = 0.75,
                 window: int = 32, increase: float = 1.0,
                 decrease: float = 0.5):
        if not (0 < min_limit <= initial <= max_limit):
            raise ValueError(
                f"need min <= initial <= max, got {min_limit}/{initial}"
                f"/{max_limit}")
        if not (0.0 < decrease < 1.0):
            raise ValueError("decrease must be a factor in (0, 1)")
        self.min_limit = int(min_limit)
        self.max_limit = int(max_limit)
        self.target_p99_s = float(target_p99_s)
        self.window = max(1, int(window))
        self.increase = float(increase)
        self.decrease = float(decrease)
        self._limit = float(initial)
        self._samples: list[float] = []  # bounded: reset every `window`
        self._lock = threading.Lock()
        # smoothed per-window p99 (seconds): the latency term the cluster
        # autoscaler compares against its own target — smoother than one
        # window's p99, fresher than the ceiling it already moved
        self.p99_ewma = 0.0
        QOS_LIMIT.set(self.ceiling)

    @property
    def ceiling(self) -> int:
        """Current concurrency ceiling (>= min_limit always)."""
        return max(self.min_limit, int(self._limit))

    def record(self, latency_s: float) -> None:
        """Feed one admitted request's queue+execute latency; adjusts the
        ceiling once per full window."""
        with self._lock:
            self._samples.append(float(latency_s))
            if len(self._samples) < self.window:
                return
            samples = sorted(self._samples)
            self._samples = []
            p99 = samples[min(len(samples) - 1,
                              int(0.99 * (len(samples) - 1)))]
            self.p99_ewma = (p99 if self.p99_ewma == 0.0
                             else 0.7 * self.p99_ewma + 0.3 * p99)
            if p99 > self.target_p99_s:
                self._limit = max(float(self.min_limit),
                                  self._limit * self.decrease)
            else:
                self._limit = min(float(self.max_limit),
                                  self._limit + self.increase)
            QOS_LIMIT.set(self.ceiling)
