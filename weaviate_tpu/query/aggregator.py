"""Aggregations over collection properties.

Reference: ``adapters/repos/db/aggregator/`` (numeric/text/bool/date
aggregations, grouped + filtered) surfaced through the Aggregate API
(``usecases/traverser/traverser_aggregate.go``). Values come from the
inverted index's per-property value map (the filterable tier), optionally
masked by a filter allow-list — the same data path the reference's
aggregator reads from LSM property buckets.
"""

from __future__ import annotations

import datetime as _dt
from collections import Counter
from typing import Any, Optional

import numpy as np

NUMERIC_AGGS = ("count", "sum", "mean", "min", "max", "median", "mode")
TEXT_AGGS = ("count", "topOccurrences")
BOOL_AGGS = (
    "count", "totalTrue", "totalFalse", "percentageTrue", "percentageFalse",
)
DATE_AGGS = ("count", "min", "max", "median", "mode")


def _parse_date(v: Any) -> Optional[_dt.datetime]:
    if isinstance(v, _dt.datetime):
        return v
    if isinstance(v, str):
        try:
            return _dt.datetime.fromisoformat(v.replace("Z", "+00:00"))
        except ValueError:
            return None
    return None


def _flatten(values: list[Any]) -> list[Any]:
    out: list[Any] = []
    for v in values:
        if isinstance(v, list):
            out.extend(v)
        else:
            out.append(v)
    return out


def aggregate_numeric(values: list[Any]) -> dict:
    nums = [float(v) for v in _flatten(values)
            if isinstance(v, (int, float)) and not isinstance(v, bool)]
    if not nums:
        return {"count": 0}
    arr = np.asarray(nums, np.float64)
    # deterministic mode: ties break to the smallest value, not insertion
    # order — the segment tier reconstructs values in key order, the RAM
    # tier sees doc order, and both must answer identically
    counts = Counter(nums)
    best = max(counts.values())
    mode_val = min(v for v, c in counts.items() if c == best)
    return {
        "count": len(nums),
        "sum": float(arr.sum()),
        "mean": float(arr.mean()),
        "min": float(arr.min()),
        "max": float(arr.max()),
        "median": float(np.median(arr)),
        "mode": mode_val,
    }


def aggregate_text(values: list[Any], top_occurrences_limit: int = 5) -> dict:
    texts = [v for v in _flatten(values) if isinstance(v, str)]
    counter = Counter(texts)
    # ties break lexicographically (engine-order independence, see mode)
    ranked = sorted(counter.items(), key=lambda t: (-t[1], t[0]))
    return {
        "count": len(texts),
        "topOccurrences": [
            {"value": v, "occurs": n}
            for v, n in ranked[:top_occurrences_limit]
        ],
    }


def aggregate_bool(values: list[Any]) -> dict:
    bools = [v for v in _flatten(values) if isinstance(v, bool)]
    n = len(bools)
    t = sum(bools)
    return {
        "count": n,
        "totalTrue": t,
        "totalFalse": n - t,
        "percentageTrue": (t / n) if n else 0.0,
        "percentageFalse": ((n - t) / n) if n else 0.0,
    }


def aggregate_date(values: list[Any]) -> dict:
    dates = [d for d in (_parse_date(v) for v in _flatten(values)) if d is not None]
    if not dates:
        return {"count": 0}
    stamps = sorted(dates)
    iso = lambda d: d.isoformat()
    dcounts = Counter(iso(d) for d in dates)
    dbest = max(dcounts.values())
    mode_val = min(v for v, c in dcounts.items() if c == dbest)
    return {
        "count": len(dates),
        "min": iso(stamps[0]),
        "max": iso(stamps[-1]),
        "median": iso(stamps[len(stamps) // 2]),
        "mode": mode_val,
    }


def aggregate_reference(values: list[Any]) -> dict:
    return {"count": len(_flatten(values))}


_BY_KIND = {
    "numeric": aggregate_numeric,
    "text": aggregate_text,
    "boolean": aggregate_bool,
    "date": aggregate_date,
    "reference": aggregate_reference,
}


def infer_kind(values: list[Any]) -> str:
    for v in _flatten(values):
        if isinstance(v, bool):
            return "boolean"
        if isinstance(v, (int, float)):
            return "numeric"
        if isinstance(v, str):
            return "date" if _parse_date(v) is not None else "text"
    return "text"


def per_doc_distinct(v):
    """A value repeated WITHIN one doc's array counts once —
    inverted-index (per-doc distinct) semantics, identical to what the
    segment tier's bitmaps can express. Shared by collection-wide and
    search-scoped aggregation so the two can never drift."""
    if isinstance(v, list):
        try:
            return list(dict.fromkeys(v))
        except TypeError:  # unhashable elements (geo dicts)
            return v
    return v


# distance-bounded (no objectLimit) search-scoped Aggregate refuses to
# truncate past this many hits — erroring beats a silently-wrong mean
DISTANCE_AGG_CAP = 100_000


def aggregate_objects(objs, props: dict, group_by=None,
                      top_occurrences_limit: int = 5) -> dict:
    """Aggregate over an explicit object list — the search-scoped
    Aggregate (reference ``traverser_aggregate.go``: near*/hybrid +
    objectLimit aggregates the top hits). Returns the same shape as
    ``Collection.aggregate`` so reply builders are shared."""
    def _vals(obj_list, prop):
        out = []
        for o in obj_list:
            v = o.properties.get(prop)
            if v is None:
                continue
            v = per_doc_distinct(v)
            out.extend(v) if isinstance(v, list) else out.append(v)
        return out

    def _props(obj_list):
        return {p: aggregate_property(_vals(obj_list, p), kind,
                                      top_occurrences_limit)
                for p, kind in props.items()}

    if group_by is None:
        return {"meta": {"count": len(objs)},
                "properties": _props(objs)}
    groups: dict = {}
    for o in objs:
        gv = o.properties.get(group_by)
        for g in (gv if isinstance(gv, list) else [gv]):
            groups.setdefault(g, []).append(o)
    return {"groups": [
        {"groupedBy": {"path": [group_by], "value": g},
         "meta": {"count": len(members)},
         "properties": _props(members)}
        for g, members in groups.items()]}


def aggregate_property(
    values: list[Any],
    kind: Optional[str] = None,
    top_occurrences_limit: int = 5,
) -> dict:
    """Aggregate one property's values; kind inferred when not given."""
    if kind is None or kind == "auto":
        kind = infer_kind(values)
    if kind == "text":
        out = aggregate_text(values, top_occurrences_limit)
    else:
        out = _BY_KIND.get(kind, aggregate_text)(values)
    out["type"] = kind
    return out
