"""Query orchestration: hybrid fusion, sorting, grouping, aggregation.

Reference: ``usecases/traverser`` (Traverser/Explorer) + ``adapters/repos/db``
post-processing (sorter, aggregator, group-by, autocut).
"""

from weaviate_tpu.query.aggregator import aggregate_property
from weaviate_tpu.query.autocut import autocut
from weaviate_tpu.query.explorer import (
    AskParams,
    Explorer,
    GenerateParams,
    Hit,
    HybridParams,
    QueryParams,
    QueryResult,
    RerankParams,
    SummaryParams,
    TokenParams,
)
from weaviate_tpu.query.fusion import ranked_fusion, relative_score_fusion
from weaviate_tpu.query.groupby import Group, GroupByParams, group_results
from weaviate_tpu.query.multi_target import combine_multi_target
from weaviate_tpu.query.sorter import sort_objects

__all__ = [
    "Explorer", "Hit", "HybridParams", "QueryParams", "QueryResult",
    "RerankParams", "GenerateParams", "AskParams", "SummaryParams",
    "TokenParams",
    "GroupByParams", "Group", "group_results", "sort_objects", "autocut",
    "ranked_fusion", "relative_score_fusion", "combine_multi_target",
    "aggregate_property",
]
