"""GroupBy: bucket search results by a property value.

Reference: ``adapters/repos/db/shard_group_by.go`` + ``entities/searchparams``
(GroupBy{Property, Groups, ObjectsPerGroup}) — results are walked best-first,
each object lands in the group keyed by its property value (array values join
each group), capped at ``groups`` groups of ``objects_per_group`` members.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from weaviate_tpu.storage.objects import StorageObject


@dataclass
class GroupByParams:
    property: str
    groups: int = 5
    objects_per_group: int = 10


@dataclass
class Group:
    value: Any
    objects: list[tuple[StorageObject, float]] = field(default_factory=list)

    @property
    def min_score(self) -> float:
        return min((s for _, s in self.objects), default=0.0)

    @property
    def max_score(self) -> float:
        return max((s for _, s in self.objects), default=0.0)


def group_results(
    results: list[tuple[StorageObject, float]],
    params: GroupByParams,
) -> list[Group]:
    """Walk results best-first into capped groups (reference shard_group_by.go)."""
    groups: dict[Any, Group] = {}
    order: list[Any] = []
    for obj, score in results:
        raw = obj.properties.get(params.property)
        keys = raw if isinstance(raw, list) else [raw]
        for key in keys:
            k = str(key) if isinstance(key, (dict,)) else key
            g = groups.get(k)
            if g is None:
                if len(groups) >= params.groups:
                    continue
                g = Group(value=k)
                groups[k] = g
                order.append(k)
            if len(g.objects) < params.objects_per_group:
                g.objects.append((obj, score))
    return [groups[k] for k in order]
