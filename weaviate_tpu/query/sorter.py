"""Property-based result sorting.

Reference: ``adapters/repos/db/sorter/`` — sorts result sets by one or more
property paths (asc/desc) with typed comparators; special paths ``id``,
``_creationTimeUnix``, ``_lastUpdateTimeUnix``. Objects missing the property
sort last regardless of order, like the reference's null handling.
"""

from __future__ import annotations

from typing import Any, Optional

from weaviate_tpu.storage.objects import StorageObject


def _sort_value(obj: StorageObject, path: str) -> Optional[Any]:
    if path in ("id", "_id", "uuid"):
        return obj.uuid
    if path == "_creationTimeUnix":
        return obj.creation_time_ms
    if path == "_lastUpdateTimeUnix":
        return obj.update_time_ms
    v = obj.properties.get(path)
    if isinstance(v, list):
        return v[0] if v else None
    if isinstance(v, bool):
        return int(v)
    return v


class _Key:
    """Comparator wrapper: missing values sort last; mixed types by repr."""

    __slots__ = ("missing", "value")

    def __init__(self, value: Any):
        self.missing = value is None
        self.value = value

    def _coerce(self, other: "_Key"):
        a, b = self.value, other.value
        if type(a) is type(b):
            return a, b
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            return a, b
        return str(a), str(b)

    def __lt__(self, other: "_Key") -> bool:
        if self.missing or other.missing:
            # missing never wins a comparison => stable, sorts last via key tuple
            return other.missing and not self.missing
        a, b = self._coerce(other)
        return a < b

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, _Key):
            return NotImplemented
        if self.missing or other.missing:
            return self.missing == other.missing
        a, b = self._coerce(other)
        return a == b


def sort_objects(
    objs: list[StorageObject],
    criteria: list[tuple[str, str]],
) -> list[StorageObject]:
    """Sort by [(property_path, "asc"|"desc"), ...], first criterion primary."""
    out = list(objs)
    # stable sort: apply criteria in reverse order
    for path, order in reversed(criteria):
        desc = order.lower() == "desc"
        # missing-last must survive reverse=True, so desc sorts the present
        # objects alone and re-appends the missing ones
        if desc:
            present = [o for o in out if _sort_value(o, path) is not None]
            missing = [o for o in out if _sort_value(o, path) is None]
            present.sort(key=lambda o: _Key(_sort_value(o, path)), reverse=True)
            out = present + missing
        else:
            out.sort(key=lambda o: (
                _sort_value(o, path) is None,
                _Key(_sort_value(o, path)),
            ))
    return out
