"""Explorer: the query orchestration façade.

Reference: ``usecases/traverser/explorer.go:132`` (GetClass) — decides
keyword vs vector vs hybrid vs plain-filtered, then applies groupBy, autocut,
sort and pagination. The REST/gRPC/GraphQL layers build a ``QueryParams`` and
call ``Explorer.get`` — the analogue of ``dto.GetParams`` flowing into the
traverser.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from weaviate_tpu.core.db import DB
from weaviate_tpu.inverted.filters import Filter
from weaviate_tpu.query.autocut import autocut as autocut_fn
from weaviate_tpu.query.groupby import Group, GroupByParams, group_results
from weaviate_tpu.query.sorter import sort_objects
from weaviate_tpu.storage.objects import StorageObject


@dataclass
class HybridParams:
    query: Optional[str] = None
    vector: Optional[np.ndarray] = None
    alpha: float = 0.75
    fusion: str = "relativeScoreFusion"
    properties: Optional[list[str]] = None
    # keyword-branch SearchOperatorOptions (reference hybrid.go:170)
    operator: str = "Or"
    minimum_match: int = 0


@dataclass
class RerankParams:
    """Reference ``modulecapabilities`` rerank additional property."""

    query: str
    property: str = ""  # document text property; "" = all text props
    module: str = "reranker-lexical"


@dataclass
class GenerateParams:
    """Reference generative additional property (singlePrompt/groupedTask)."""

    single_prompt: Optional[str] = None  # "{prop}" placeholders
    grouped_task: Optional[str] = None
    properties: Optional[list[str]] = None  # context props for grouped
    module: str = "generative-template"


@dataclass
class AskParams:
    """Reference ``qna-*`` GraphQL ``ask`` argument: answer a question from
    the best-matching object's text."""

    question: str
    properties: Optional[list[str]] = None  # context props; None = all text
    certainty: float = 0.0  # drop answers below this confidence
    module: str = "qna-transformers"


@dataclass
class SummaryParams:
    """Reference ``sum-transformers`` ``_additional { summary }``."""

    properties: list[str] = field(default_factory=list)
    module: str = "sum-transformers"


@dataclass
class TokenParams:
    """Reference ``ner-transformers`` ``_additional { tokens }``."""

    properties: list[str] = field(default_factory=list)
    certainty: float = 0.0
    module: str = "ner-transformers"


@dataclass
class QueryParams:
    collection: str
    tenant: str = ""
    limit: int = 10
    offset: int = 0
    filters: Optional[Filter] = None
    # nearText: vectorized via the collection's vectorizer module
    near_text: Optional[str] = None
    # concept movement (reference nearText moveTo/moveAwayFrom):
    # {"concepts": [...], "objects": [uuid, ...], "force": float}
    near_text_move_to: Optional[dict] = None
    near_text_move_away: Optional[dict] = None
    # vector search (single or multi target)
    near_vector: Optional[np.ndarray] = None
    target_vector: str = ""
    targets: Optional[dict[str, np.ndarray]] = None  # multi-target
    target_combination: str = "minimum"
    target_weights: Optional[dict[str, float]] = None
    max_distance: Optional[float] = None
    # keyword search
    bm25_query: Optional[str] = None
    bm25_properties: Optional[list[str]] = None
    # SearchOperatorOptions (reference base_search.proto:38): "And"
    # requires every query token; minimum_match bounds "Or"
    bm25_operator: str = "Or"
    bm25_minimum_match: int = 0
    # hybrid
    hybrid: Optional[HybridParams] = None
    # post-processing
    # exhaustive-cursor pagination (reference filters.Cursor): only
    # valid for plain fetches — no search/sort/filters. None = no
    # cursor; "" = cursor from the start (uuid order, reference REST
    # ``?after=`` semantics)
    after: Optional[str] = None
    sort: list[tuple[str, str]] = field(default_factory=list)
    group_by: Optional[GroupByParams] = None
    # legacy GraphQL group: {type: closest|merge, force} (reference
    # traverser/grouper; distinct from groupBy)
    legacy_group: Optional[dict] = None
    autocut: int = 0
    # module-powered additional properties
    rerank: Optional[RerankParams] = None
    generate: Optional[GenerateParams] = None
    ask: Optional[AskParams] = None
    summary: Optional[SummaryParams] = None
    tokens: Optional[TokenParams] = None
    # query spellcheck (reference text-spellcheck): autocorrect nearText /
    # bm25 input before vectorization when enabled
    autocorrect: bool = False


@dataclass
class Hit:
    object: StorageObject
    score: Optional[float] = None  # higher is better (bm25/hybrid)
    distance: Optional[float] = None  # lower is better (vector)
    additional: dict[str, Any] = field(default_factory=dict)


@dataclass
class QueryResult:
    hits: list[Hit] = field(default_factory=list)
    groups: Optional[list[Group]] = None
    generated: Optional[str] = None  # groupedTask output


class Explorer:
    def __init__(self, db: DB):
        self.db = db

    def _query_vector(self, col, text: str) -> np.ndarray:
        """nearText → query vector via the collection's vectorizer module
        (reference ``near_params_vector.go``)."""
        name = col.config.vectorizer
        if name == "none" or col.modules is None:
            raise ValueError(
                f"collection {col.config.name!r} has no vectorizer: "
                "nearText requires one (use nearVector instead)"
            )
        return col.modules.vectorizer(name).vectorize_query(text)

    def _apply_moves(self, col, vector: np.ndarray,
                     move_to: Optional[dict], move_away: Optional[dict],
                     tenant: str = "") -> np.ndarray:
        """nearText concept movement (reference
        ``nearText/searcher_movements.go``): moveTo lerps toward the
        target with weight force*0.5; moveAwayFrom pushes along
        (source - target) by the same weight. Targets average the
        vectorized concepts plus the named objects' vectors."""
        def _target(move: dict) -> Optional[np.ndarray]:
            parts = []
            for concept in move.get("concepts") or ():
                parts.append(np.asarray(
                    self._query_vector(col, concept), np.float32))
            for uuid in move.get("objects") or ():
                obj = col.get(uuid, tenant=tenant)
                if obj is None or obj.vector is None:
                    raise ValueError(
                        f"move object {uuid!r} not found or has no "
                        "vector")
                parts.append(np.asarray(obj.vector, np.float32))
            if not parts:
                return None
            return np.mean(np.stack(parts), axis=0)

        vector = np.asarray(vector, np.float32)
        if move_to and float(move_to.get("force", 0)) > 0:
            t = _target(move_to)
            if t is not None:
                w = float(move_to["force"]) * 0.5
                vector = vector * (1.0 - w) + t * w
        if move_away and float(move_away.get("force", 0)) > 0:
            t = _target(move_away)
            if t is not None:
                w = float(move_away["force"]) * 0.5
                vector = vector + w * (vector - t)
        return vector

    def get(self, params: QueryParams) -> QueryResult:
        col = self.db.get_collection(params.collection)
        fetch = params.offset + params.limit
        if params.after is not None and (
                params.filters is not None
                or params.near_vector is not None
                or params.near_text is not None
                or params.bm25_query is not None
                or params.hybrid is not None or params.targets):
            # reference restriction: the exhaustive cursor is a plain
            # scan; ranked or filtered orders have no stable cursor
            raise ValueError(
                "cursor pagination (after) requires a plain fetch "
                "without search operators or filters")
        scored: list[tuple[StorageObject, float]] = []
        kind = "none"

        if params.autocorrect and col.modules is not None \
                and col.modules.has("text-spellcheck"):
            checker = col.modules.spellchecker("text-spellcheck")
            if params.near_text is not None:
                params.near_text = checker.check(params.near_text)["corrected"]
            if params.bm25_query is not None:
                params.bm25_query = checker.check(
                    params.bm25_query)["corrected"]
        if params.near_text is not None and params.near_vector is None \
                and params.hybrid is None:
            params.near_vector = self._apply_moves(
                col, self._query_vector(col, params.near_text),
                params.near_text_move_to, params.near_text_move_away,
                params.tenant)
        if params.hybrid is not None and params.hybrid.vector is None \
                and params.hybrid.query and col.config.vectorizer != "none" \
                and col.modules is not None:
            # hybrid with text only: vectorize the query for the dense branch
            params.hybrid.vector = self._query_vector(col, params.hybrid.query)

        if params.hybrid is not None:
            h = params.hybrid
            scored = col.hybrid_search(
                query=h.query, vector=h.vector, alpha=h.alpha, k=fetch,
                fusion=h.fusion, properties=h.properties,
                flt=params.filters, tenant=params.tenant,
                target=params.target_vector,
                max_vector_distance=params.max_distance,
                operator=h.operator, minimum_match=h.minimum_match,
            )
            kind = "score"
        elif params.targets:
            scored = col.multi_target_search(
                params.targets, k=fetch,
                combination=params.target_combination,
                weights=params.target_weights,
                flt=params.filters, tenant=params.tenant,
            )
            kind = "distance"
        elif params.near_vector is not None:
            scored = col.vector_search(
                params.near_vector, k=fetch, target=params.target_vector,
                flt=params.filters, tenant=params.tenant,
                max_distance=params.max_distance,
            )
            kind = "distance"
        elif params.bm25_query is not None:
            scored = col.bm25_search(
                params.bm25_query, k=fetch,
                properties=params.bm25_properties,
                flt=params.filters, tenant=params.tenant,
                operator=params.bm25_operator,
                minimum_match=params.bm25_minimum_match,
            )
            kind = "score"
        elif params.filters is not None:
            # a sort over unranked results must see the FULL candidate
            # set — sorting a pre-truncated page returns the first
            # objects reordered, not the global order (reference sorts
            # at the shard against the whole allowlist, sorter/)
            want = (1 << 62) if params.sort else fetch
            objs = col.filter_search(params.filters, limit=want,
                                     tenant=params.tenant)
            scored = [(o, 0.0) for o in objs]
        else:
            if params.after is not None and (params.sort or params.offset):
                raise ValueError(
                    "cursor pagination (after) cannot combine with "
                    "sort or offset")
            # offset applies once, in the common paging below — passing
            # it here too double-applied it (offset=10 returned [])
            want = (1 << 62) if params.sort else fetch
            objs = col.objects_page(limit=want, offset=0,
                                    tenant=params.tenant,
                                    after=params.after)
            scored = [(o, 0.0) for o in objs]

        # autocut applies to ranked results only (reference entities/autocut)
        if params.autocut > 0 and kind != "none":
            cut = autocut_fn([s for _, s in scored], params.autocut)
            scored = scored[:cut]

        # groupBy bypasses sort/pagination (reference shard_group_by.go)
        if params.group_by is not None:
            groups = group_results(scored, params.group_by)
            return QueryResult(hits=[], groups=groups)

        if params.sort:
            # Ranked queries sort the already-fetched top-k, matching
            # the reference (index.go:1630 sorts the merged per-shard
            # top-limit results); only UNRANKED fetches widen to the
            # full candidate set above.
            ordered = sort_objects([o for o, _ in scored], params.sort)
            by_id = {id(o): s for o, s in scored}
            scored = [(o, by_id.get(id(o), 0.0)) for o in ordered]

        page = scored[params.offset: params.offset + params.limit]
        hits = [
            Hit(object=o,
                score=s if kind == "score" else None,
                distance=s if kind == "distance" else None)
            for o, s in page
        ]
        if params.legacy_group is not None:
            from weaviate_tpu.query.legacy_group import legacy_group

            hits = legacy_group(
                hits,
                str(params.legacy_group.get("type", "closest")),
                float(params.legacy_group.get("force", 0.0)))
        result = QueryResult(hits=hits)
        if params.rerank is not None:
            self._apply_rerank(col, result, params.rerank)
        if params.generate is not None:
            self._apply_generate(col, result, params.generate)
        if params.ask is not None:
            self._apply_ask(col, result, params.ask)
        if params.summary is not None:
            self._apply_summary(col, result, params.summary)
        if params.tokens is not None:
            self._apply_tokens(col, result, params.tokens)
        return result

    def _doc_text(self, obj: StorageObject, prop: str) -> str:
        if prop:
            v = obj.properties.get(prop, "")
            return " ".join(v) if isinstance(v, list) else str(v)
        return " ".join(
            str(v) for v in obj.properties.values()
            if isinstance(v, str)
        )

    def _apply_rerank(self, col, result: QueryResult,
                      params: RerankParams) -> None:
        """Rerank hits by module score; reorders and annotates
        (reference reranker additional property)."""
        if col.modules is None or not result.hits:
            return
        reranker = col.modules.reranker(params.module)
        docs = [self._doc_text(h.object, params.property) for h in result.hits]
        scores = reranker.rerank(params.query, docs)
        for h, s in zip(result.hits, scores):
            h.additional["rerank_score"] = float(s)
        result.hits.sort(key=lambda h: -h.additional["rerank_score"])

    def _apply_generate(self, col, result: QueryResult,
                        params: GenerateParams) -> None:
        """Generative additional property (reference generate provider)."""
        if col.modules is None or not result.hits:
            return
        gen = col.modules.generative(params.module)
        if params.single_prompt:
            for h in result.hits:
                h.additional["generate"] = gen.generate_single(
                    params.single_prompt, h.object.properties
                )
        if params.grouped_task:
            props = params.properties
            docs = []
            for h in result.hits:
                if props:
                    docs.append(" ".join(
                        str(h.object.properties.get(p, "")) for p in props
                    ))
                else:
                    docs.append(self._doc_text(h.object, ""))
            result.generated = gen.generate(
                params.grouped_task, docs, grouped=True
            )

    def _apply_ask(self, col, result: QueryResult,
                   params: AskParams) -> None:
        """QnA additional property: answer from the TOP hit's text, like the
        reference (``qna-transformers`` answers over result objects and the
        first confident answer wins)."""
        if col.modules is None or not result.hits:
            return
        qna = col.modules.qna(params.module)
        for h in result.hits:
            props = params.properties
            if props:
                ctx = " ".join(
                    str(h.object.properties.get(p, "")) for p in props)
            else:
                ctx = self._doc_text(h.object, "")
            if not ctx.strip():
                continue
            a = qna.answer(params.question, ctx)
            if a.get("answer") and a.get("certainty", 0.0) >= params.certainty:
                h.additional["answer"] = a
                # reference returns the first (best-ranked) answer and
                # stops — remaining hits carry no answer payload
                break

    def _apply_summary(self, col, result: QueryResult,
                       params: SummaryParams) -> None:
        if col.modules is None or not result.hits:
            return
        summ = col.modules.summarizer(params.module)
        for h in result.hits:
            out = []
            for p in (params.properties
                      or [k for k, v in h.object.properties.items()
                          if isinstance(v, str)]):
                v = h.object.properties.get(p)
                if isinstance(v, str) and v.strip():
                    out.append({"property": p, "result": summ.summarize(v)})
            if out:
                h.additional["summary"] = out

    def _apply_tokens(self, col, result: QueryResult,
                      params: TokenParams) -> None:
        if col.modules is None or not result.hits:
            return
        ner = col.modules.ner(params.module)
        for h in result.hits:
            toks = []
            for p in (params.properties
                      or [k for k, v in h.object.properties.items()
                          if isinstance(v, str)]):
                v = h.object.properties.get(p)
                if not isinstance(v, str) or not v.strip():
                    continue
                for e in ner.tag(v):
                    if e.get("certainty", 0.0) >= params.certainty:
                        toks.append({"property": p, **e})
            if toks:
                h.additional["tokens"] = toks

    def aggregate(
        self,
        collection: str,
        properties: Optional[dict[str, Optional[str]]] = None,
        filters: Optional[Filter] = None,
        group_by: Optional[str] = None,
        tenant: str = "",
    ) -> dict:
        col = self.db.get_collection(collection)
        return col.aggregate(properties=properties, flt=filters,
                             group_by=group_by, tenant=tenant)
