"""Explorer: the query orchestration façade.

Reference: ``usecases/traverser/explorer.go:132`` (GetClass) — decides
keyword vs vector vs hybrid vs plain-filtered, then applies groupBy, autocut,
sort and pagination. The REST/gRPC/GraphQL layers build a ``QueryParams`` and
call ``Explorer.get`` — the analogue of ``dto.GetParams`` flowing into the
traverser.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from weaviate_tpu.core.db import DB
from weaviate_tpu.inverted.filters import Filter
from weaviate_tpu.query.autocut import autocut as autocut_fn
from weaviate_tpu.query.groupby import Group, GroupByParams, group_results
from weaviate_tpu.query.sorter import sort_objects
from weaviate_tpu.storage.objects import StorageObject


@dataclass
class HybridParams:
    query: Optional[str] = None
    vector: Optional[np.ndarray] = None
    alpha: float = 0.75
    fusion: str = "relativeScoreFusion"
    properties: Optional[list[str]] = None


@dataclass
class QueryParams:
    collection: str
    tenant: str = ""
    limit: int = 10
    offset: int = 0
    filters: Optional[Filter] = None
    # vector search (single or multi target)
    near_vector: Optional[np.ndarray] = None
    target_vector: str = ""
    targets: Optional[dict[str, np.ndarray]] = None  # multi-target
    target_combination: str = "minimum"
    target_weights: Optional[dict[str, float]] = None
    max_distance: Optional[float] = None
    # keyword search
    bm25_query: Optional[str] = None
    bm25_properties: Optional[list[str]] = None
    # hybrid
    hybrid: Optional[HybridParams] = None
    # post-processing
    sort: list[tuple[str, str]] = field(default_factory=list)
    group_by: Optional[GroupByParams] = None
    autocut: int = 0


@dataclass
class Hit:
    object: StorageObject
    score: Optional[float] = None  # higher is better (bm25/hybrid)
    distance: Optional[float] = None  # lower is better (vector)


@dataclass
class QueryResult:
    hits: list[Hit] = field(default_factory=list)
    groups: Optional[list[Group]] = None


class Explorer:
    def __init__(self, db: DB):
        self.db = db

    def get(self, params: QueryParams) -> QueryResult:
        col = self.db.get_collection(params.collection)
        fetch = params.offset + params.limit
        scored: list[tuple[StorageObject, float]] = []
        kind = "none"

        if params.hybrid is not None:
            h = params.hybrid
            scored = col.hybrid_search(
                query=h.query, vector=h.vector, alpha=h.alpha, k=fetch,
                fusion=h.fusion, properties=h.properties,
                flt=params.filters, tenant=params.tenant,
                target=params.target_vector,
                max_vector_distance=params.max_distance,
            )
            kind = "score"
        elif params.targets:
            scored = col.multi_target_search(
                params.targets, k=fetch,
                combination=params.target_combination,
                weights=params.target_weights,
                flt=params.filters, tenant=params.tenant,
            )
            kind = "distance"
        elif params.near_vector is not None:
            scored = col.vector_search(
                params.near_vector, k=fetch, target=params.target_vector,
                flt=params.filters, tenant=params.tenant,
                max_distance=params.max_distance,
            )
            kind = "distance"
        elif params.bm25_query is not None:
            scored = col.bm25_search(
                params.bm25_query, k=fetch,
                properties=params.bm25_properties,
                flt=params.filters, tenant=params.tenant,
            )
            kind = "score"
        elif params.filters is not None:
            objs = col.filter_search(params.filters, limit=fetch,
                                     tenant=params.tenant)
            scored = [(o, 0.0) for o in objs]
        else:
            objs = col.objects_page(limit=params.limit, offset=params.offset,
                                    tenant=params.tenant)
            scored = [(o, 0.0) for o in objs]

        # autocut applies to ranked results only (reference entities/autocut)
        if params.autocut > 0 and kind != "none":
            cut = autocut_fn([s for _, s in scored], params.autocut)
            scored = scored[:cut]

        # groupBy bypasses sort/pagination (reference shard_group_by.go)
        if params.group_by is not None:
            groups = group_results(scored, params.group_by)
            return QueryResult(hits=[], groups=groups)

        if params.sort:
            ordered = sort_objects([o for o, _ in scored], params.sort)
            by_id = {id(o): s for o, s in scored}
            scored = [(o, by_id.get(id(o), 0.0)) for o in ordered]

        page = scored[params.offset: params.offset + params.limit]
        hits = [
            Hit(object=o,
                score=s if kind == "score" else None,
                distance=s if kind == "distance" else None)
            for o, s in page
        ]
        return QueryResult(hits=hits)

    def aggregate(
        self,
        collection: str,
        properties: Optional[dict[str, Optional[str]]] = None,
        filters: Optional[Filter] = None,
        group_by: Optional[str] = None,
        tenant: str = "",
    ) -> dict:
        col = self.db.get_collection(collection)
        return col.aggregate(properties=properties, flt=filters,
                             group_by=group_by, tenant=tenant)
