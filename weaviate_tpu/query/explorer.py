"""Explorer: the query orchestration façade.

Reference: ``usecases/traverser/explorer.go:132`` (GetClass) — decides
keyword vs vector vs hybrid vs plain-filtered, then applies groupBy, autocut,
sort and pagination. The REST/gRPC/GraphQL layers build a ``QueryParams`` and
call ``Explorer.get`` — the analogue of ``dto.GetParams`` flowing into the
traverser.
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from weaviate_tpu.core.db import DB
from weaviate_tpu.inverted.filters import Filter
from weaviate_tpu.query.autocut import autocut as autocut_fn
from weaviate_tpu.query.groupby import Group, GroupByParams, group_results
from weaviate_tpu.query.sorter import sort_objects
from weaviate_tpu.storage.objects import StorageObject


@dataclass
class HybridParams:
    query: Optional[str] = None
    vector: Optional[np.ndarray] = None
    alpha: float = 0.75
    fusion: str = "relativeScoreFusion"
    properties: Optional[list[str]] = None
    # keyword-branch SearchOperatorOptions (reference hybrid.go:170)
    operator: str = "Or"
    minimum_match: int = 0


@dataclass
class RerankParams:
    """Reference ``modulecapabilities`` rerank additional property.

    ``module`` "" = collection default: the target index's configured
    DEVICE module when one exists (fused into the search dispatch, see
    docs/modules.md), else the host ``reranker-lexical``. Naming a
    registered device module routes the fused tier; any other name runs
    the host module tier after search."""

    query: str
    property: str = ""  # document text property; "" = all text props
    module: str = ""


@dataclass
class GenerateParams:
    """Reference generative additional property (singlePrompt/groupedTask)."""

    single_prompt: Optional[str] = None  # "{prop}" placeholders
    grouped_task: Optional[str] = None
    properties: Optional[list[str]] = None  # context props for grouped
    module: str = "generative-template"


@dataclass
class AskParams:
    """Reference ``qna-*`` GraphQL ``ask`` argument: answer a question from
    the best-matching object's text."""

    question: str
    properties: Optional[list[str]] = None  # context props; None = all text
    certainty: float = 0.0  # drop answers below this confidence
    module: str = "qna-transformers"


@dataclass
class SummaryParams:
    """Reference ``sum-transformers`` ``_additional { summary }``."""

    properties: list[str] = field(default_factory=list)
    module: str = "sum-transformers"


@dataclass
class TokenParams:
    """Reference ``ner-transformers`` ``_additional { tokens }``."""

    properties: list[str] = field(default_factory=list)
    certainty: float = 0.0
    module: str = "ner-transformers"


@dataclass
class QueryParams:
    collection: str
    tenant: str = ""
    limit: int = 10
    offset: int = 0
    filters: Optional[Filter] = None
    # nearText: vectorized via the collection's vectorizer module
    near_text: Optional[str] = None
    # concept movement (reference nearText moveTo/moveAwayFrom):
    # {"concepts": [...], "objects": [uuid, ...], "force": float}
    near_text_move_to: Optional[dict] = None
    near_text_move_away: Optional[dict] = None
    # vector search (single or multi target)
    near_vector: Optional[np.ndarray] = None
    target_vector: str = ""
    targets: Optional[dict[str, np.ndarray]] = None  # multi-target
    target_combination: str = "minimum"
    target_weights: Optional[dict[str, float]] = None
    max_distance: Optional[float] = None
    # keyword search
    bm25_query: Optional[str] = None
    bm25_properties: Optional[list[str]] = None
    # SearchOperatorOptions (reference base_search.proto:38): "And"
    # requires every query token; minimum_match bounds "Or"
    bm25_operator: str = "Or"
    bm25_minimum_match: int = 0
    # hybrid
    hybrid: Optional[HybridParams] = None
    # post-processing
    # exhaustive-cursor pagination (reference filters.Cursor): only
    # valid for plain fetches — no search/sort/filters. None = no
    # cursor; "" = cursor from the start (uuid order, reference REST
    # ``?after=`` semantics)
    after: Optional[str] = None
    sort: list[tuple[str, str]] = field(default_factory=list)
    group_by: Optional[GroupByParams] = None
    # legacy GraphQL group: {type: closest|merge, force} (reference
    # traverser/grouper; distinct from groupBy)
    legacy_group: Optional[dict] = None
    autocut: int = 0
    # module-powered additional properties
    rerank: Optional[RerankParams] = None
    generate: Optional[GenerateParams] = None
    ask: Optional[AskParams] = None
    summary: Optional[SummaryParams] = None
    tokens: Optional[TokenParams] = None
    # query spellcheck (reference text-spellcheck): autocorrect nearText /
    # bm25 input before vectorization when enabled
    autocorrect: bool = False


@dataclass
class Hit:
    object: StorageObject
    score: Optional[float] = None  # higher is better (bm25/hybrid)
    distance: Optional[float] = None  # lower is better (vector)
    additional: dict[str, Any] = field(default_factory=dict)


@dataclass
class QueryResult:
    hits: list[Hit] = field(default_factory=list)
    groups: Optional[list[Group]] = None
    generated: Optional[str] = None  # groupedTask output


class Explorer:
    def __init__(self, db: DB):
        self.db = db

    def _query_vector(self, col, text: str) -> np.ndarray:
        """nearText → query vector via the collection's vectorizer module
        (reference ``near_params_vector.go``)."""
        name = col.config.vectorizer
        if name == "none" or col.modules is None:
            raise ValueError(
                f"collection {col.config.name!r} has no vectorizer: "
                "nearText requires one (use nearVector instead)"
            )
        return col.modules.vectorizer(name).vectorize_query(text)

    def _apply_moves(self, col, vector: np.ndarray,
                     move_to: Optional[dict], move_away: Optional[dict],
                     tenant: str = "") -> np.ndarray:
        """nearText concept movement (reference
        ``nearText/searcher_movements.go``): moveTo lerps toward the
        target with weight force*0.5; moveAwayFrom pushes along
        (source - target) by the same weight. Targets average the
        vectorized concepts plus the named objects' vectors."""
        def _target(move: dict) -> Optional[np.ndarray]:
            parts = []
            for concept in move.get("concepts") or ():
                parts.append(np.asarray(
                    self._query_vector(col, concept), np.float32))
            for uuid in move.get("objects") or ():
                obj = col.get(uuid, tenant=tenant)
                if obj is None or obj.vector is None:
                    raise ValueError(
                        f"move object {uuid!r} not found or has no "
                        "vector")
                parts.append(np.asarray(obj.vector, np.float32))
            if not parts:
                return None
            return np.mean(np.stack(parts), axis=0)

        vector = np.asarray(vector, np.float32)
        if move_to and float(move_to.get("force", 0)) > 0:
            t = _target(move_to)
            if t is not None:
                w = float(move_to["force"]) * 0.5
                vector = vector * (1.0 - w) + t * w
        if move_away and float(move_away.get("force", 0)) > 0:
            t = _target(move_away)
            if t is not None:
                w = float(move_away["force"]) * 0.5
                vector = vector + w * (vector - t)
        return vector

    def get(self, params: QueryParams) -> QueryResult:
        col = self.db.get_collection(params.collection)
        fetch = params.offset + params.limit
        if params.after is not None and (
                params.filters is not None
                or params.near_vector is not None
                or params.near_text is not None
                or params.bm25_query is not None
                or params.hybrid is not None or params.targets):
            # reference restriction: the exhaustive cursor is a plain
            # scan; ranked or filtered orders have no stable cursor
            raise ValueError(
                "cursor pagination (after) requires a plain fetch "
                "without search operators or filters")
        scored: list[tuple[StorageObject, float]] = []
        kind = "none"
        fused_rerank = None  # set when the device tier scores in-dispatch

        if params.autocorrect and col.modules is not None \
                and col.modules.has("text-spellcheck"):
            checker = col.modules.spellchecker("text-spellcheck")
            if params.near_text is not None:
                params.near_text = checker.check(params.near_text)["corrected"]
            if params.bm25_query is not None:
                params.bm25_query = checker.check(
                    params.bm25_query)["corrected"]
        if params.near_text is not None and params.near_vector is None \
                and params.hybrid is None:
            params.near_vector = self._apply_moves(
                col, self._query_vector(col, params.near_text),
                params.near_text_move_to, params.near_text_move_away,
                params.tenant)
        if params.hybrid is not None:
            # reject unknown fusion names BEFORE any leg work (or query
            # vectorization) — every surface maps this ValueError to
            # 400 / INVALID_ARGUMENT, never a 500
            from weaviate_tpu.query.fusion import validate_fusion

            validate_fusion(params.hybrid.fusion)
        if params.hybrid is not None and params.hybrid.vector is None \
                and params.hybrid.query and col.config.vectorizer != "none" \
                and col.modules is not None:
            # hybrid with text only: vectorize the query for the dense branch
            params.hybrid.vector = self._query_vector(col, params.hybrid.query)

        if params.hybrid is not None:
            h = params.hybrid
            scored = col.hybrid_search(
                query=h.query, vector=h.vector, alpha=h.alpha, k=fetch,
                fusion=h.fusion, properties=h.properties,
                flt=params.filters, tenant=params.tenant,
                target=params.target_vector,
                max_vector_distance=params.max_distance,
                operator=h.operator, minimum_match=h.minimum_match,
            )
            kind = "score"
        elif params.targets:
            scored = col.multi_target_search(
                params.targets, k=fetch,
                combination=params.target_combination,
                weights=params.target_weights,
                flt=params.filters, tenant=params.tenant,
            )
            kind = "distance"
        elif params.near_vector is not None:
            fused_rerank = self._fused_rerank_request(col, params)
            scored = col.vector_search(
                params.near_vector, k=fetch, target=params.target_vector,
                flt=params.filters, tenant=params.tenant,
                max_distance=params.max_distance,
                rerank=fused_rerank,
            )
            kind = "distance"
        elif params.bm25_query is not None:
            scored = col.bm25_search(
                params.bm25_query, k=fetch,
                properties=params.bm25_properties,
                flt=params.filters, tenant=params.tenant,
                operator=params.bm25_operator,
                minimum_match=params.bm25_minimum_match,
            )
            kind = "score"
        elif params.filters is not None:
            # a sort over unranked results must see the FULL candidate
            # set — sorting a pre-truncated page returns the first
            # objects reordered, not the global order (reference sorts
            # at the shard against the whole allowlist, sorter/)
            want = (1 << 62) if params.sort else fetch
            objs = col.filter_search(params.filters, limit=want,
                                     tenant=params.tenant)
            scored = [(o, 0.0) for o in objs]
        else:
            if params.after is not None and (params.sort or params.offset):
                raise ValueError(
                    "cursor pagination (after) cannot combine with "
                    "sort or offset")
            # offset applies once, in the common paging below — passing
            # it here too double-applied it (offset=10 returned [])
            want = (1 << 62) if params.sort else fetch
            objs = col.objects_page(limit=want, offset=0,
                                    tenant=params.tenant,
                                    after=params.after)
            scored = [(o, 0.0) for o in objs]

        # autocut applies to ranked results only (reference entities/autocut)
        if params.autocut > 0 and kind != "none":
            cut = autocut_fn([s for _, s in scored], params.autocut)
            scored = scored[:cut]

        # groupBy bypasses sort/pagination (reference shard_group_by.go)
        if params.group_by is not None:
            groups = group_results(scored, params.group_by)
            return QueryResult(hits=[], groups=groups)

        if params.sort:
            # Ranked queries sort the already-fetched top-k, matching
            # the reference (index.go:1630 sorts the merged per-shard
            # top-limit results); only UNRANKED fetches widen to the
            # full candidate set above.
            ordered = sort_objects([o for o, _ in scored], params.sort)
            by_id = {id(o): s for o, s in scored}
            scored = [(o, by_id.get(id(o), 0.0)) for o in ordered]

        page = scored[params.offset: params.offset + params.limit]
        hits = [
            Hit(object=o,
                score=s if kind == "score" else None,
                distance=s if kind == "distance" else None)
            for o, s in page
        ]
        if params.legacy_group is not None:
            from weaviate_tpu.query.legacy_group import legacy_group

            hits = legacy_group(
                hits,
                str(params.legacy_group.get("type", "closest")),
                float(params.legacy_group.get("force", 0.0)))
        result = QueryResult(hits=hits)
        if params.rerank is not None:
            if fused_rerank is not None or self._rerank_inherent(
                    col, params):
                # the device module scored INSIDE the search dispatch
                # (the fused hnsw stage, or the multivector index whose
                # serving path IS the fused scan+rerank): each hit's
                # distance is its negated module score, no host rerank
                # pass runs — and must not overwrite the ordering
                for h in result.hits:
                    if h.distance is not None:
                        h.additional["rerank_score"] = -float(h.distance)
            else:
                if not params.rerank.module:
                    # "" = collection default. If that default is a
                    # DEVICE module, silently substituting the lexical
                    # reranker on a non-fusable query shape would swap
                    # the ranking criterion without a trace — reject
                    # like the explicit spelling does
                    cfg = (col.config.named_vectors.get(
                        params.target_vector) if params.target_vector
                        else col.config.vector_config)
                    rcfg = getattr(cfg, "rerank", None)
                    if rcfg is not None and rcfg.enabled:
                        raise ValueError(
                            f"this collection's default rerank module "
                            f"{rcfg.module!r} is a device module and "
                            "cannot serve this query shape (bm25/hybrid "
                            "result set or max_distance bound) — name a "
                            "host reranker explicitly, e.g. module: "
                            "\"reranker-lexical\"")
                self._apply_rerank(col, result, params.rerank)
        if params.generate is not None:
            self._apply_generate(col, result, params.generate)
        if params.ask is not None:
            self._apply_ask(col, result, params.ask)
        if params.summary is not None:
            self._apply_summary(col, result, params.summary)
        if params.tokens is not None:
            self._apply_tokens(col, result, params.tokens)
        return result

    def _doc_text(self, obj: StorageObject, prop: str) -> str:
        if prop:
            v = obj.properties.get(prop, "")
            return " ".join(v) if isinstance(v, list) else str(v)
        return " ".join(
            str(v) for v in obj.properties.values()
            if isinstance(v, str)
        )

    def _fused_rerank_request(self, col, params: QueryParams):
        """A ``RerankRequest`` when this query's rerank should ride the
        fused device stage (the target index is an hnsw index with a
        device module configured and the requested module is
        device-capable), else None — the host module tier applies after
        search instead. The rerank ``query`` TEXT becomes the query
        token set via the collection's vectorizer (the stated criterion
        is honored, not silently swapped for the search vector); with
        no vectorizer the search vector itself is the token set (self
        mode). ``property`` selects document TEXT and has no meaning on
        the device tier — token planes are vectors."""
        rr = params.rerank
        if rr is None or params.max_distance is not None:
            return None
        cfg = (col.config.named_vectors.get(params.target_vector)
               if params.target_vector else col.config.vector_config)
        rcfg = getattr(cfg, "rerank", None)
        if rcfg is None or not rcfg.enabled \
                or getattr(cfg, "index_type", "") != "hnsw":
            return None
        from weaviate_tpu.modules.device.base import (
            RerankRequest,
            build_device_reranker,
            device_reranker_catalog,
        )

        name = rr.module or rcfg.module
        if name not in device_reranker_catalog():
            return None  # a host module was asked for by name
        mod_params = rcfg.params if name == rcfg.module else None
        q_tokens = None
        if rr.query and col.modules is not None \
                and col.config.vectorizer != "none":
            from weaviate_tpu.modules.base import ModuleNotAvailable

            try:
                q_tokens = col.modules.vectorizer(
                    col.config.vectorizer).vectorize_query(rr.query)
            except ModuleNotAvailable:
                q_tokens = None  # self mode; the provider is offline
        return RerankRequest(build_device_reranker(name, mod_params),
                             q_tokens)

    def _rerank_inherent(self, col, params: QueryParams) -> bool:
        """Whether the target index's OWN serving path already applied
        the requested device module — a multivector index reranks every
        search with its configured module (default MaxSim), so the
        rerank{} block annotates rather than re-sorts. A DIFFERENT
        module name (host or device) falls through to _apply_rerank,
        which either runs the host module or rejects a device name with
        a clean error. NOTE: on a multivector target the late
        interaction is scored against the SEARCH token set — the
        rerank ``query`` text is informational here (re-stating the
        criterion in multivector token space would need a text2multivec
        provider); docs/modules.md spells this out."""
        if params.near_vector is None:
            return False
        cfg = (col.config.named_vectors.get(params.target_vector)
               if params.target_vector else col.config.vector_config)
        if getattr(cfg, "index_type", "") != "multivector":
            return False
        rcfg = getattr(cfg, "rerank", None)
        configured = (rcfg.module if rcfg is not None and rcfg.enabled
                      else "rerank-maxsim")
        return (params.rerank.module or configured) == configured

    @contextmanager
    def _module_scope(self, span_name: str, **attrs):
        """Host module stage harness: re-enter the request scope (the
        module may run on a pool thread that never inherited it — this
        re-activates the INGRESS span so the stage's child span lands in
        the request's trace) and hold the stage to the request's serving
        deadline. Yields a callable the stage invokes between documents:
        a slow reranker/generator sheds at the next document boundary
        instead of blowing past QoS budgets unobserved."""
        from weaviate_tpu.monitoring.tracing import TRACER
        from weaviate_tpu.serving import context as serving_ctx

        ctx = serving_ctx.current()
        deadline = ctx.deadline if ctx is not None else None

        def checkpoint() -> None:
            if deadline is not None:
                deadline.require()

        with serving_ctx.request_scope(ctx), \
                TRACER.span(span_name, **attrs):
            checkpoint()
            yield checkpoint

    def _apply_rerank(self, col, result: QueryResult,
                      params: RerankParams) -> None:
        """HOST-tier rerank: module scores after search returns
        (reference reranker additional property). Runs under the
        request's serving deadline inside the ingress trace — and counts
        itself, so host-tier rerank traffic is attributable next to the
        fused tier's."""
        if col.modules is None or not result.hits:
            return
        from weaviate_tpu.monitoring.metrics import RERANK_REQUESTS

        name = params.module or "reranker-lexical"
        if col.modules.has_device_reranker(name):
            # a device module reached the host tier: this query shape
            # cannot fuse (bm25/hybrid result set, max_distance bound,
            # or no device rerank config on the target index) and a
            # device module has no document-text scorer to fall back to
            raise ValueError(
                f"module {name!r} is a device rerank module; it fuses "
                "into nearVector searches on an index configured with "
                "a rerank module (docs/modules.md) — use a host "
                "reranker (e.g. 'reranker-lexical') for this query")
        reranker = col.modules.reranker(name)
        RERANK_REQUESTS.inc(module=name, tier="host")
        with self._module_scope("modules.rerank", module=name,
                                hits=len(result.hits)) as checkpoint:
            docs = [self._doc_text(h.object, params.property)
                    for h in result.hits]
            checkpoint()
            scores = reranker.rerank(params.query, docs)
        for h, s in zip(result.hits, scores):
            h.additional["rerank_score"] = float(s)
        result.hits.sort(key=lambda h: -h.additional["rerank_score"])

    def _apply_generate(self, col, result: QueryResult,
                        params: GenerateParams) -> None:
        """Generative additional property (reference generate provider).
        Deadline-checked between documents — generation is the slowest
        module stage and must shed mid-result, not after."""
        if col.modules is None or not result.hits:
            return
        gen = col.modules.generative(params.module)
        with self._module_scope("modules.generate", module=params.module,
                                hits=len(result.hits)) as checkpoint:
            if params.single_prompt:
                for h in result.hits:
                    checkpoint()
                    h.additional["generate"] = gen.generate_single(
                        params.single_prompt, h.object.properties
                    )
            if params.grouped_task:
                props = params.properties
                docs = []
                for h in result.hits:
                    if props:
                        docs.append(" ".join(
                            str(h.object.properties.get(p, ""))
                            for p in props
                        ))
                    else:
                        docs.append(self._doc_text(h.object, ""))
                checkpoint()
                result.generated = gen.generate(
                    params.grouped_task, docs, grouped=True
                )

    def _apply_ask(self, col, result: QueryResult,
                   params: AskParams) -> None:
        """QnA additional property: answer from the TOP hit's text, like the
        reference (``qna-transformers`` answers over result objects and the
        first confident answer wins)."""
        if col.modules is None or not result.hits:
            return
        qna = col.modules.qna(params.module)
        for h in result.hits:
            props = params.properties
            if props:
                ctx = " ".join(
                    str(h.object.properties.get(p, "")) for p in props)
            else:
                ctx = self._doc_text(h.object, "")
            if not ctx.strip():
                continue
            a = qna.answer(params.question, ctx)
            if a.get("answer") and a.get("certainty", 0.0) >= params.certainty:
                h.additional["answer"] = a
                # reference returns the first (best-ranked) answer and
                # stops — remaining hits carry no answer payload
                break

    def _apply_summary(self, col, result: QueryResult,
                       params: SummaryParams) -> None:
        if col.modules is None or not result.hits:
            return
        summ = col.modules.summarizer(params.module)
        for h in result.hits:
            out = []
            for p in (params.properties
                      or [k for k, v in h.object.properties.items()
                          if isinstance(v, str)]):
                v = h.object.properties.get(p)
                if isinstance(v, str) and v.strip():
                    out.append({"property": p, "result": summ.summarize(v)})
            if out:
                h.additional["summary"] = out

    def _apply_tokens(self, col, result: QueryResult,
                      params: TokenParams) -> None:
        if col.modules is None or not result.hits:
            return
        ner = col.modules.ner(params.module)
        for h in result.hits:
            toks = []
            for p in (params.properties
                      or [k for k, v in h.object.properties.items()
                          if isinstance(v, str)]):
                v = h.object.properties.get(p)
                if not isinstance(v, str) or not v.strip():
                    continue
                for e in ner.tag(v):
                    if e.get("certainty", 0.0) >= params.certainty:
                        toks.append({"property": p, **e})
            if toks:
                h.additional["tokens"] = toks

    def aggregate(
        self,
        collection: str,
        properties: Optional[dict[str, Optional[str]]] = None,
        filters: Optional[Filter] = None,
        group_by: Optional[str] = None,
        tenant: str = "",
    ) -> dict:
        col = self.db.get_collection(collection)
        return col.aggregate(properties=properties, flt=filters,
                             group_by=group_by, tenant=tenant)
