"""Autocut: truncate a result list at the Nth score discontinuity.

Reference: ``entities/autocut/autocut.go`` — given scores sorted best-first,
divide the score range into per-result average steps; every gap larger than
the average step counts as a "jump"; keep results up to the Nth jump.
"""

from __future__ import annotations


def autocut(scores: list[float], n_jumps: int) -> int:
    """Return the cut index (exclusive) after the ``n_jumps``-th discontinuity.

    ``scores`` are sorted best-first (descending for similarities, ascending
    for distances — only the deltas matter). ``n_jumps <= 0`` disables.
    """
    if n_jumps <= 0 or len(scores) <= 1:
        return len(scores)
    total = abs(scores[-1] - scores[0])
    if total == 0:
        return len(scores)
    avg_step = total / len(scores)
    jumps = 0
    for i in range(1, len(scores)):
        gap = abs(scores[i] - scores[i - 1])
        if gap > avg_step:
            jumps += 1
            if jumps >= n_jumps:
                return i
    return len(scores)
