"""Multi-target-vector score combination.

Reference: ``adapters/repos/db/shard_combine_multi_target.go`` +
``usecases/traverser/target_vector_param_helper.go`` — a query against several
named vectors runs one search per target, joins by doc, fills in missing
distances by recomputing them exactly, and combines with one of: sum, average,
minimum, manual weights, relative score.
"""

from __future__ import annotations

import numpy as np

COMBINATIONS = ("sum", "average", "minimum", "manualWeights", "relativeScore")


def np_distance(q: np.ndarray, v: np.ndarray, metric: str) -> float:
    """Exact single-pair distance on host, matching ops.distance semantics."""
    q = np.asarray(q, np.float32)
    v = np.asarray(v, np.float32)
    if metric == "l2-squared":
        d = q - v
        return float(np.dot(d, d))
    if metric == "dot":
        return float(-np.dot(q, v))
    if metric == "cosine":
        qn = q / max(float(np.linalg.norm(q)), 1e-12)
        vn = v / max(float(np.linalg.norm(v)), 1e-12)
        return float(1.0 - np.dot(qn, vn))
    if metric == "manhattan":
        return float(np.abs(q - v).sum())
    if metric == "hamming":
        return float(np.sum(q != v))
    raise ValueError(f"unknown metric {metric!r}")


def join_mode(combination: str) -> str:
    """Map an API combination to the fused kernel's static join variant.
    sum / average / manualWeights all lower to ONE "weighted" program —
    only the traced weight rows differ — so they share a compile."""
    if combination == "minimum":
        return "minimum"
    if combination == "relativeScore":
        return "relative"
    return "weighted"


def weight_row(targets: list[str], combination: str,
               weights: dict[str, float] | None) -> np.ndarray:
    """Per-target weight row [T] feeding the kernel's traced ``weights``
    input, reproducing the host oracle's arithmetic exactly: sum → 1,
    average → 1/T, manualWeights/relativeScore → caller weights
    (default 1), minimum → ones (the join ignores them)."""
    t = len(targets)
    if combination == "average":
        return np.full(t, 1.0 / t, np.float32)
    if combination in ("manualWeights", "relativeScore"):
        return np.asarray([(weights or {}).get(tg, 1.0) for tg in targets],
                          np.float32)
    return np.ones(t, np.float32)


def validate_multi_target(
    targets: list[str], combination: str,
    weights: dict[str, float] | None, known_targets,
) -> None:
    """Request-shape validation shared by every API surface: raises
    ``ValueError`` (GraphQL errors / 400 at REST, INVALID_ARGUMENT at
    gRPC) on unknown targets, duplicate targets, unknown combination,
    or weight/target-set mismatch."""
    if not targets:
        raise ValueError("multi-target search requires at least one "
                         "target vector")
    if len(set(targets)) != len(targets):
        raise ValueError("duplicate target vectors in targetVectors")
    known = set(known_targets)
    for t in targets:
        if t not in known:
            raise ValueError(f"unknown target vector {t!r}")
    if combination not in COMBINATIONS:
        raise ValueError(f"unknown combination {combination!r}")
    if weights:
        if combination not in ("manualWeights", "relativeScore"):
            raise ValueError(
                "targetVectors weights require the manualWeights or "
                f"relativeScore combination, not {combination!r}")
        extra = set(weights) - set(targets)
        if extra:
            raise ValueError(
                f"weights name unknown targets: {sorted(extra)}")
        if combination == "manualWeights" and set(weights) != set(targets):
            missing = set(targets) - set(weights)
            raise ValueError(
                "manualWeights requires one weight per target; missing: "
                f"{sorted(missing)}")


def combine_multi_target(
    per_target: dict[str, dict], combination: str,
    weights: dict[str, float] | None = None,
) -> list[tuple[object, float]]:
    """Join per-target results into one ranking (ascending combined distance).

    ``per_target``: target -> {key: distance} with every key present in every
    target (callers fill gaps by exact recompute first). Returns
    [(key, combined)] sorted ascending.
    """
    if combination not in COMBINATIONS:
        raise ValueError(f"unknown combination {combination!r}")
    targets = list(per_target.keys())
    keys = set()
    for dists in per_target.values():
        keys.update(dists.keys())
    keys = list(keys)
    if not keys:
        return []

    mat = np.asarray(
        [[per_target[t].get(k, np.inf) for k in keys] for t in targets],
        np.float64,
    )  # [T, K]

    if combination == "minimum":
        combined = mat.min(axis=0)
    elif combination == "sum":
        combined = mat.sum(axis=0)
    elif combination == "average":
        combined = mat.mean(axis=0)
    elif combination == "manualWeights":
        w = np.asarray([(weights or {}).get(t, 1.0) for t in targets])
        combined = (mat * w[:, None]).sum(axis=0)
    else:  # relativeScore: min-max normalize each target's distances first
        lo = mat.min(axis=1, keepdims=True)
        hi = mat.max(axis=1, keepdims=True)
        span = np.where(hi - lo <= 0, 1.0, hi - lo)
        norm = (mat - lo) / span
        w = np.asarray([(weights or {}).get(t, 1.0) for t in targets])
        combined = (norm * w[:, None]).sum(axis=0)

    order = np.argsort(combined, kind="stable")
    return [(keys[i], float(combined[i])) for i in order]
