"""Hybrid fusion algorithms.

Reference: ``usecases/traverser/hybrid/hybrid_fusion.go`` — rankedFusion
(``:22``, reciprocal-rank with a 60 offset) and relativeScoreFusion (``:93``,
min-max normalize each branch then weighted sum). Keys are object UUIDs so
fusion works across shards — and across NODES: the coordinator fuses the
globally merged per-leg candidate sets, so relativeScoreFusion's min-max
normalization spans the whole corpus, never one shard's skewed slice.

Two tiers serve the same semantics. ``fuse_result_sets`` routes to the
device kernels (``ops/fusion.py``: one jitted scatter + top_k per hybrid
request) and keeps the pure-python functions below as the exact twin —
the parity oracle for tests AND the fallback tier, which latches LOUDLY
(``weaviate_tpu_hybrid_fallback_total`` + a span event) the way the
rerank tier's host fallback does.
"""

from __future__ import annotations

import logging
from typing import Any, Hashable, Optional

logger = logging.getLogger("weaviate_tpu.query.fusion")

# the classic RRF constant used by the reference
RANKED_FUSION_OFFSET = 60.0


def ranked_fusion(
    result_sets: list[list[tuple[Hashable, float]]],
    weights: list[float],
    k: int,
) -> list[tuple[Hashable, float]]:
    """Reciprocal-rank fusion: score = Σ_set weight / (60 + rank).

    Each result set is [(key, score)] sorted best-first; scores themselves
    are ignored, only ranks matter.
    """
    fused: dict[Hashable, float] = {}
    for rs, w in zip(result_sets, weights):
        for rank, (key, _score) in enumerate(rs):
            fused[key] = fused.get(key, 0.0) + w / (RANKED_FUSION_OFFSET + rank)
    out = sorted(fused.items(), key=lambda t: -t[1])
    return out[:k]


def relative_score_fusion(
    result_sets: list[list[tuple[Hashable, float]]],
    weights: list[float],
    k: int,
) -> list[tuple[Hashable, float]]:
    """Min-max normalize each branch's scores to [0,1], then weighted sum.

    Scores must be "higher is better" in every set (invert distances before
    calling). Matches the reference's relativeScoreFusion (:93): a set with
    a single distinct score normalizes to 1.0.
    """
    fused: dict[Hashable, float] = {}
    for rs, w in zip(result_sets, weights):
        if not rs:
            continue
        scores = [s for _, s in rs]
        lo, hi = min(scores), max(scores)
        span = hi - lo
        for key, s in rs:
            norm = 1.0 if span <= 0 else (s - lo) / span
            fused[key] = fused.get(key, 0.0) + w * norm
    out = sorted(fused.items(), key=lambda t: -t[1])
    return out[:k]


FUSION_ALGORITHMS = {
    "rankedFusion": ranked_fusion,
    "relativeScoreFusion": relative_score_fusion,
}


def hybrid_fetch(k: int) -> int:
    """Per-leg over-fetch: ceil(hybrid_overfetch_factor · k), never below
    k. THE one definition — the collection path, the cluster
    coordinator, and the prewarm fusion lattice must all derive the same
    fetch or prewarm compiles shapes traffic never dispatches."""
    import math

    from weaviate_tpu.utils.runtime_config import HYBRID_OVERFETCH_FACTOR

    factor = max(1.0, float(HYBRID_OVERFETCH_FACTOR.get()))
    return max(k, int(math.ceil(k * factor)))


def validate_fusion(name: str) -> None:
    """Reject unknown fusion names with a clean ValueError — mapped to
    400 / INVALID_ARGUMENT at every API surface, never a 500."""
    if name not in FUSION_ALGORITHMS:
        raise ValueError(
            f"unknown fusion algorithm {name!r} (expected one of "
            f"{sorted(FUSION_ALGORITHMS)})")


def assemble_slots(
    result_sets: list[list[tuple[Hashable, float]]],
) -> tuple[list[Hashable], list[list[int]], list[list[float]]]:
    """Dense union-slot encoding of the legs' (key, score) lists.

    Slot ids are assigned in the host twin's dict-insertion order (leg 0
    in rank order, then each later leg's NEW keys in rank order), so the
    device kernel's lower-index-wins tie-break reproduces the host's
    stable-sort order exactly. Returns (keys by slot, per-leg slot
    lists, per-leg score lists).
    """
    slot_of: dict[Hashable, int] = {}
    keys: list[Hashable] = []
    slot_sets: list[list[int]] = []
    score_sets: list[list[float]] = []
    for rs in result_sets:
        slots = []
        scores = []
        for key, score in rs:
            idx = slot_of.get(key)
            if idx is None:
                idx = slot_of[key] = len(keys)
                keys.append(key)
            slots.append(idx)
            scores.append(float(score))
        slot_sets.append(slots)
        score_sets.append(scores)
    return keys, slot_sets, score_sets


def _latch_fallback(reason: str, exc: Optional[BaseException]) -> None:
    """The fallback tier is never silent: counter + span event + log."""
    from weaviate_tpu.monitoring import tracing
    from weaviate_tpu.monitoring.metrics import HYBRID_FALLBACK

    HYBRID_FALLBACK.inc(stage="fuse", reason=reason)
    span = tracing.current_span()
    if span is not None:
        span.add_event("hybrid.fuse.fallback", reason=reason)
    if exc is not None:
        logger.warning("device hybrid fusion fell back to host (%s): %s",
                       reason, exc)


def device_fusion_enabled() -> bool:
    from weaviate_tpu.utils.runtime_config import HYBRID_DEVICE_FUSION

    return str(HYBRID_DEVICE_FUSION.get()).lower() not in (
        "off", "0", "false")


def fuse_result_sets(
    result_sets: list[list[tuple[Hashable, float]]],
    weights: list[float],
    k: int,
    algorithm: str,
) -> list[tuple[Hashable, float]]:
    """Fuse the legs on device (one jitted dispatch), falling back to
    the exact host twin — loudly — when the device tier is disabled or
    errors. Same contract as the host functions: [(key, fused score)]
    best-first, at most ``k`` entries."""
    validate_fusion(algorithm)
    if not any(result_sets):
        return []
    if not device_fusion_enabled():
        _latch_fallback("disabled", None)
        return FUSION_ALGORITHMS[algorithm](result_sets, weights, k)
    keys, slot_sets, score_sets = assemble_slots(result_sets)
    try:
        from weaviate_tpu.ops.fusion import fuse_topk

        ids, vals = fuse_topk(slot_sets, score_sets, weights, k,
                              algorithm, len(keys))
    except Exception as e:  # device tier down: serve host, latch loudly
        _latch_fallback("device_error", e)
        return FUSION_ALGORITHMS[algorithm](result_sets, weights, k)
    return [(keys[int(i)], float(v)) for i, v in zip(ids, vals)]
