"""Hybrid fusion algorithms.

Reference: ``usecases/traverser/hybrid/hybrid_fusion.go`` — rankedFusion
(``:22``, reciprocal-rank with a 60 offset) and relativeScoreFusion (``:93``,
min-max normalize each branch then weighted sum). Keys are object UUIDs so
fusion works across shards.
"""

from __future__ import annotations

from typing import Any, Hashable

# the classic RRF constant used by the reference
RANKED_FUSION_OFFSET = 60.0


def ranked_fusion(
    result_sets: list[list[tuple[Hashable, float]]],
    weights: list[float],
    k: int,
) -> list[tuple[Hashable, float]]:
    """Reciprocal-rank fusion: score = Σ_set weight / (60 + rank).

    Each result set is [(key, score)] sorted best-first; scores themselves
    are ignored, only ranks matter.
    """
    fused: dict[Hashable, float] = {}
    for rs, w in zip(result_sets, weights):
        for rank, (key, _score) in enumerate(rs):
            fused[key] = fused.get(key, 0.0) + w / (RANKED_FUSION_OFFSET + rank)
    out = sorted(fused.items(), key=lambda t: -t[1])
    return out[:k]


def relative_score_fusion(
    result_sets: list[list[tuple[Hashable, float]]],
    weights: list[float],
    k: int,
) -> list[tuple[Hashable, float]]:
    """Min-max normalize each branch's scores to [0,1], then weighted sum.

    Scores must be "higher is better" in every set (invert distances before
    calling). Matches the reference's relativeScoreFusion (:93): a set with
    a single distinct score normalizes to 1.0.
    """
    fused: dict[Hashable, float] = {}
    for rs, w in zip(result_sets, weights):
        if not rs:
            continue
        scores = [s for _, s in rs]
        lo, hi = min(scores), max(scores)
        span = hi - lo
        for key, s in rs:
            norm = 1.0 if span <= 0 else (s - lo) / span
            fused[key] = fused.get(key, 0.0) + w * norm
    out = sorted(fused.items(), key=lambda t: -t[1])
    return out[:k]


FUSION_ALGORITHMS = {
    "rankedFusion": ranked_fusion,
    "relativeScoreFusion": relative_score_fusion,
}
