"""Cost-based query planner + resident filter planes (docs/planner.md).

``plan(stats)`` picks exact-scan / filtered-beam / over-fetch-post-filter
per query from inverted-index selectivity estimates; ``FilterPlaneStore``
keeps hot predicates as device-resident bitmaps the dispatcher coalesces
by ``(plane_id, version)``.
"""

from weaviate_tpu.query.planner.cost import (  # noqa: F401
    PLAN_BEAM,
    PLAN_EXACT,
    PLAN_OVERFETCH,
    PLAN_UNFILTERED,
    Plan,
    PlanStats,
    expansion_budget,
    plan,
)
from weaviate_tpu.query.planner.planes import (  # noqa: F401
    FilterPlane,
    FilterPlaneStore,
    canonical_key,
    matches,
)
