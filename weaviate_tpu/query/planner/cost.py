"""Cost-based plan choice for filtered vector search — pure + explainable.

Three executable plans (docs/planner.md has the full taxonomy):

- ``exact_scan``           — masked flat top-k over allowed rows only
  (pre-filter, exact; the MXU eats small allowed sets for breakfast).
- ``filtered_beam``        — device graph walk with the allow mask on
  device and a two-hop ACORN expansion budget that widens *through*
  blocked neighbors (ops/device_beam.py).
- ``overfetch_postfilter`` — unfiltered device walk over-fetched by
  ~1/selectivity, filtered on host (Weaviate's classic post-filter
  switch); only viable at high selectivity where the over-fetch stays
  inside the kernel's widest bucket.

``plan()`` is a pure function of :class:`PlanStats` — no clocks, no
globals, no I/O — so plan choices are unit-testable against seeded stats
and reproducible from the trace attributes they emit
(``planner.plan`` / ``planner.reason`` / ``planner.cost_*``).

Cost unit: estimated vector-distance evaluations on device. The config
knobs ``flat_search_cutoff`` / ``filter_flat_selectivity`` act as hard
pre-filter guards first (identical semantics to the pre-planner triage,
so existing deployments keep their behavior); the cost race only decides
among plans that are recall-viable past the guards.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

PLAN_UNFILTERED = "unfiltered"
PLAN_EXACT = "exact_scan"
PLAN_BEAM = "filtered_beam"
PLAN_OVERFETCH = "overfetch_postfilter"

# widest walk the device kernel will bucket to before over-fetch stops
# being viable (pow2 bucketing in hnsw._device_beam_search)
_EF_CAP = 2048
# two-hop expansion budget ceiling: each unit gathers one extra adjacency
# row per beam step, so the budget is decades-of-selectivity, not 1/sel
_MAX_EXPANSION = 4


@dataclass(frozen=True)
class PlanStats:
    """Everything ``plan()`` is allowed to know. ``selectivity`` is the
    allowed fraction of live docs — exact when the caller already holds a
    mask or plane popcount (``exact_count=True``), otherwise the inverted
    index's sketch estimate (``estimate_selectivity``)."""

    live: int
    k: int
    ef: int
    selectivity: float
    exact_count: bool = False
    plane_resident: bool = False
    flat_cutoff: int = 40000
    flat_selectivity: float = 0.35
    graph_degree: int = 32
    mesh: bool = False


@dataclass(frozen=True)
class Plan:
    """The chosen plan + enough context to explain it in a trace span."""

    plan_type: str
    expansion: int        # two-hop budget per beam step (filtered_beam)
    fetch_k: int          # device fetch width (overfetch_postfilter)
    est_selectivity: float
    est_allowed: int
    cost_exact: float
    cost_beam: float
    cost_overfetch: float
    reason: str

    def trace_attrs(self) -> dict:
        """Span attributes — the explainability contract of docs/planner.md."""
        return {
            "planner.plan": self.plan_type,
            "planner.reason": self.reason,
            "planner.selectivity": round(self.est_selectivity, 6),
            "planner.allowed": self.est_allowed,
            "planner.expansion": self.expansion,
            "planner.fetch_k": self.fetch_k,
            "planner.cost_exact": round(self.cost_exact, 1),
            "planner.cost_beam": round(self.cost_beam, 1),
            "planner.cost_overfetch": round(self.cost_overfetch, 1),
        }


def expansion_budget(selectivity: float) -> int:
    """Selectivity-scaled two-hop budget: one extra adjacency row per
    decade of selectivity below 100% (1% -> 2, 0.1% -> 3), capped."""
    if selectivity >= 0.5:
        return 0
    decades = math.ceil(math.log10(1.0 / max(selectivity, 1e-9)))
    return max(1, min(_MAX_EXPANSION, decades))


def plan(stats: PlanStats) -> "Plan":
    """Pick the cheapest recall-viable plan for one filtered query."""
    live = max(1, stats.live)
    sel = min(1.0, max(0.0, stats.selectivity))
    allowed = int(round(sel * live))
    fetch = max(stats.k, min(stats.ef, 2 * stats.k))

    def mk(plan_type, expansion, fetch_k, ce, cb, co, reason):
        return Plan(plan_type, expansion, fetch_k, sel, allowed,
                    ce, cb, co, reason)

    if sel >= 1.0:
        return mk(PLAN_UNFILTERED, 0, fetch, 0.0, 0.0, 0.0,
                  "filter passes everything")

    expansion = expansion_budget(sel)
    # cost race (unit: device distance evals)
    cost_exact = float(live)
    # beam converges in O(ef) expansions of graph_degree neighbors; the
    # two-hop budget multiplies the per-step gather. An ad-hoc filter
    # additionally pays a host mask AND + upload, amortized here as
    # live/8 (byte traffic, not distance math — a deliberate thumb on
    # the scale toward plans that reuse a resident plane).
    mask_rent = 0.0 if stats.plane_resident else live / 8.0
    cost_beam = stats.ef * stats.graph_degree * (1 + expansion) + mask_rent
    # over-fetch must surface k allowed among ~fetch/sel candidates
    fetch_over = int(math.ceil(fetch / max(sel, 1.0 / live)))
    if fetch_over <= _EF_CAP:
        cost_overfetch = (stats.ef * stats.graph_degree) / max(sel, 1e-9)
    else:
        cost_overfetch = math.inf

    # hard pre-filter guards — same routing the pre-planner triage used
    if allowed <= stats.k:
        return mk(PLAN_EXACT, 0, fetch, cost_exact, cost_beam,
                  cost_overfetch, f"allowed={allowed} <= k={stats.k}")
    if allowed <= stats.flat_cutoff:
        return mk(PLAN_EXACT, 0, fetch, cost_exact, cost_beam,
                  cost_overfetch,
                  f"allowed={allowed} <= flat_search_cutoff="
                  f"{stats.flat_cutoff}")
    if sel <= stats.flat_selectivity:
        return mk(PLAN_EXACT, 0, fetch, cost_exact, cost_beam,
                  cost_overfetch,
                  f"selectivity={sel:.4f} <= filter_flat_selectivity="
                  f"{stats.flat_selectivity}")

    best = min(cost_exact, cost_beam, cost_overfetch)
    if best == cost_beam:
        return mk(PLAN_BEAM, expansion, fetch, cost_exact, cost_beam,
                  cost_overfetch,
                  "beam cheapest"
                  + (" (plane resident)" if stats.plane_resident else ""))
    if best == cost_overfetch:
        return mk(PLAN_OVERFETCH, 0, min(_EF_CAP, fetch_over), cost_exact,
                  cost_beam, cost_overfetch,
                  f"over-fetch x{fetch_over // max(1, fetch)} cheapest at "
                  f"selectivity {sel:.3f}")
    return mk(PLAN_EXACT, 0, fetch, cost_exact, cost_beam, cost_overfetch,
              "exact scan cheapest")
