"""Resident filter planes: hot predicates as device-resident bitmaps.

A *filter plane* is one predicate compiled to a dense bool bitmap over a
shard's doc-id space, kept hot:

- **host side** it is maintained incrementally on every put/delete (the
  per-doc :func:`matches` evaluator for the supported operator subset;
  unsupported operators mark the plane stale and it rebuilds lazily from
  the inverted index — exact either way),
- **device side** it is uploaded once per (version, mutation) state and
  reused across queries — row-sharded along the mesh ``shard`` axis like
  every other plane when a mesh is up — and the dispatcher coalesces
  filtered requests by ``(plane_id, version)`` instead of digesting full
  masks (index/dispatch.py).

Planes come from two sources: collection config (``resident_filters`` —
declared hot predicates) and auto-promotion (an ad-hoc filter seen
``filter_plane_promote_hits`` times). Their HBM bytes are charged to the
tiering ledger through ``Shard.hbm_bytes`` and detach/attach with the
shard's residency moves (demote drops the device mirror; the next search
after promote re-uploads).

Torn reads are acceptable by design: a search racing an insert may see
the bit either way — the same consistency stance as the live mask.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Callable, Optional

import numpy as np

from weaviate_tpu.inverted.filters import Filter, like_to_regex

# operators the per-doc evaluator maintains incrementally; anything else
# (geo, reference joins) flips the plane to stale-on-write + lazy rebuild
_INCREMENTAL_OPS = frozenset((
    "And", "Or", "Not", "Equal", "NotEqual", "GreaterThan",
    "GreaterThanEqual", "LessThan", "LessThanEqual", "Like",
    "ContainsAny", "ContainsAll", "IsNull",
))


def canonical_key(flt: Filter) -> str:
    """Stable identity of a predicate: sorted-key JSON of its AST."""
    return json.dumps(flt.to_dict(), sort_keys=True, separators=(",", ":"))


def _plane_id(key: str) -> str:
    return hashlib.blake2b(key.encode("utf-8"), digest_size=6).hexdigest()


def _supported(flt: Filter) -> bool:
    if flt.operator not in _INCREMENTAL_OPS:
        return False
    # reference joins traverse other collections — per-doc eval can't
    if flt.path is not None and len(flt.path) >= 3:
        return False
    return all(_supported(o) for o in flt.operands)


def _eq_scalar(v: Any, target: Any) -> bool:
    if isinstance(v, bool) != isinstance(target, bool):
        return False
    if isinstance(v, (int, float)) and isinstance(target, (int, float)):
        return float(v) == float(target)
    return v == target


def matches(flt: Filter, properties: dict) -> bool:
    """Per-doc predicate eval mirroring ``columnar.eval_leaf`` semantics
    (NotEqual only matches docs that HAVE the property; list values match
    if any element matches). Only call for :func:`_supported` trees."""
    op = flt.operator
    if op == "And":
        return all(matches(o, properties) for o in flt.operands)
    if op == "Or":
        return any(matches(o, properties) for o in flt.operands)
    if op == "Not":
        return not matches(flt.operands[0], properties)

    prop = flt.path[-1]
    val = properties.get(prop)
    if op == "IsNull":
        has = val is not None
        want_null = flt.value in (True, None)
        return (not has) if want_null else has
    if val is None:
        return False
    vals = val if isinstance(val, list) else [val]
    if op == "Equal":
        return any(_eq_scalar(v, flt.value) for v in vals)
    if op == "NotEqual":
        # multi-valued docs always carry some value != fv (columnar.py)
        if len(vals) > 1:
            return True
        return not _eq_scalar(vals[0], flt.value)
    if op in ("GreaterThan", "GreaterThanEqual", "LessThan",
              "LessThanEqual"):
        t = flt.value
        out = False
        for v in vals:
            if isinstance(v, bool) or not isinstance(v, (int, float, str)):
                continue
            if isinstance(v, str) != isinstance(t, str):
                continue
            if op == "GreaterThan":
                out = out or v > t
            elif op == "GreaterThanEqual":
                out = out or v >= t
            elif op == "LessThan":
                out = out or v < t
            else:
                out = out or v <= t
        return out
    if op == "Like":
        rx = like_to_regex(str(flt.value))
        return any(isinstance(v, str) and rx.match(v) is not None
                   for v in vals)
    if op == "ContainsAny":
        wanted = flt.value if isinstance(flt.value, list) else [flt.value]
        return any(any(_eq_scalar(v, w) for v in vals) for w in wanted)
    if op == "ContainsAll":
        wanted = flt.value if isinstance(flt.value, list) else [flt.value]
        if not wanted:
            return False
        return all(any(_eq_scalar(v, w) for v in vals) for w in wanted)
    raise ValueError(f"matches() on unsupported operator {op!r}")


class FilterPlane:
    """One resident predicate bitmap (see module doc)."""

    def __init__(self, flt: Filter, key: Optional[str] = None):
        self.flt = flt
        self.key = key if key is not None else canonical_key(flt)
        self.plane_id = _plane_id(self.key)
        self.incremental = _supported(flt)
        # version: structural identity of the bitmap — bumps on rebuild,
        # NOT on incremental bit flips, so the dispatcher's
        # (plane_id, version) group key coalesces across live ingest
        self.version = 0
        self.hits = 0
        self.stale = True  # built on first lookup
        self._bits = np.zeros(0, bool)
        self._mut = 0          # host mutation counter (device dirtiness)
        self._count: Optional[tuple[int, int]] = None  # (_mut, popcount)
        self._dev = None       # jnp mirror
        self._dev_state = None  # (version, _mut, cap, sharding key)
        self._grow_lock = threading.Lock()

    # -- host bitmap -------------------------------------------------------
    def _ensure(self, n: int) -> None:
        if n <= len(self._bits):
            return
        with self._grow_lock:
            if n > len(self._bits):
                grown = np.zeros(max(n, 2 * len(self._bits), 1024), bool)
                grown[: len(self._bits)] = self._bits
                self._bits = grown

    def set(self, doc_id: int, value: bool) -> None:
        self._ensure(doc_id + 1)
        if bool(self._bits[doc_id]) != value:
            self._bits[doc_id] = value
            self._mut += 1
            self._count = None

    def rebuild(self, mask: np.ndarray) -> None:
        """Replace the bitmap wholesale (promotion / stale recovery)."""
        self._bits = np.asarray(mask, bool).copy()
        self.version += 1
        self._mut += 1
        self._count = None
        self.stale = False

    def mask(self, space: int) -> np.ndarray:
        """Dense bool mask over ``space`` doc ids (zero-padded view)."""
        b = self._bits
        if len(b) == space:
            return b
        if len(b) > space:
            return b[:space]
        out = np.zeros(space, bool)
        out[: len(b)] = b
        return out

    def count(self) -> int:
        c = self._count
        if c is not None and c[0] == self._mut:
            return c[1]
        n = int(np.count_nonzero(self._bits))
        self._count = (self._mut, n)
        return n

    # -- device mirror -----------------------------------------------------
    def device_mask(self, cap: int, sharding=None):
        """The plane's device-resident mirror, padded to ``cap`` and placed
        with ``sharding`` (row-sharded along the mesh shard axis when one
        is up). Cached until a host bit flips or the plane rebuilds —
        repeat filtered queries pay zero upload."""
        state = (self.version, self._mut, cap,
                 None if sharding is None else id(sharding))
        if self._dev is not None and self._dev_state == state:
            return self._dev
        import jax

        host = self.mask(cap)
        if sharding is not None:
            dev = jax.device_put(host, sharding)
        else:
            dev = jax.device_put(host)
        self._dev = dev
        self._dev_state = state
        return dev

    def hbm_bytes(self) -> int:
        return int(self._dev.nbytes) if self._dev is not None else 0

    def drop_device(self) -> int:
        """Detach the device mirror (tiering demote); returns bytes freed
        so callers keep the ledger honest (device-array-leak contract)."""
        freed = self.hbm_bytes()
        self._dev = None
        self._dev_state = None
        return freed

    def nbytes_host(self) -> int:
        return int(self._bits.nbytes)

    def summary(self) -> dict:
        return {
            "plane_id": self.plane_id,
            "version": self.version,
            "hits": self.hits,
            "incremental": self.incremental,
            "stale": self.stale,
            "count": self.count(),
            "hbm_bytes": self.hbm_bytes(),
            "filter": self.flt.to_dict(),
        }


class FilterPlaneStore:
    """All resident planes of one shard.

    ``recompute(flt) -> mask`` is the exact evaluator (inverted index ∧
    live mask), used at promotion and stale recovery. ``on_put`` /
    ``on_delete`` ride the shard's durable write path; searches call
    ``lookup`` which also drives hit-counting auto-promotion."""

    def __init__(self, recompute: Callable[[Filter], np.ndarray]):
        self._recompute = recompute
        self._lock = threading.Lock()
        self._planes: dict[str, FilterPlane] = {}
        self._hits: dict[str, tuple[int, Filter]] = {}

    def _knobs(self) -> tuple[int, int]:
        from weaviate_tpu.utils.runtime_config import (
            FILTER_PLANE_MAX, FILTER_PLANE_PROMOTE_HITS,
        )

        return int(FILTER_PLANE_PROMOTE_HITS.get()), int(
            FILTER_PLANE_MAX.get())

    def declare(self, flt: Filter) -> FilterPlane:
        """Register a config-declared plane (built on first lookup)."""
        key = canonical_key(flt)
        with self._lock:
            plane = self._planes.get(key)
            if plane is None:
                plane = self._planes[key] = FilterPlane(flt, key)
            return plane

    def lookup(self, flt: Filter) -> Optional[FilterPlane]:
        """The search-path entry: returns a ready plane for ``flt`` or
        None (counting the miss toward auto-promotion)."""
        key = canonical_key(flt)
        plane = self._planes.get(key)
        if plane is None:
            promote_hits, max_planes = self._knobs()
            if promote_hits <= 0:
                return None
            with self._lock:
                plane = self._planes.get(key)
                if plane is None:
                    hits, _ = self._hits.get(key, (0, flt))
                    hits += 1
                    if hits >= promote_hits \
                            and len(self._planes) < max_planes:
                        plane = self._planes[key] = FilterPlane(flt, key)
                        self._hits.pop(key, None)
                    else:
                        self._hits[key] = (hits, flt)
                        if len(self._hits) > 256:  # bound the miss table
                            self._hits.pop(next(iter(self._hits)))
                        return None
        plane.hits += 1
        if plane.stale:
            with self._lock:
                if plane.stale:
                    plane.rebuild(self._recompute(plane.flt))
        return plane

    # -- write-path maintenance -------------------------------------------
    def on_put(self, doc_id: int, properties: dict) -> None:
        for plane in self._planes.values():
            if plane.stale:
                continue
            if plane.incremental:
                plane.set(doc_id, matches(plane.flt, properties))
            else:
                plane.stale = True  # lazy rebuild at next lookup

    def on_delete(self, doc_id: int) -> None:
        for plane in self._planes.values():
            if not plane.stale:
                plane.set(doc_id, False)

    # -- residency ---------------------------------------------------------
    def hbm_bytes(self) -> int:
        return sum(p.hbm_bytes() for p in self._planes.values())

    def host_bytes(self) -> int:
        return sum(p.nbytes_host() for p in self._planes.values())

    def drop_device(self) -> int:
        """Detach every device mirror; returns total bytes freed."""
        return sum(p.drop_device() for p in self._planes.values())

    def __len__(self) -> int:
        return len(self._planes)

    def planes(self) -> list[FilterPlane]:
        return list(self._planes.values())

    def stats(self) -> dict:
        return {
            "planes": [p.summary() for p in self._planes.values()],
            "hbm_bytes": self.hbm_bytes(),
            "host_bytes": self.host_bytes(),
            "pending": {k: h for k, (h, _) in self._hits.items()},
        }
