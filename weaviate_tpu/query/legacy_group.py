"""Legacy vector grouping — the GraphQL ``group: {type, force}`` arg.

Reference: ``usecases/traverser/grouper`` — greedy single-link
clustering of the result set by normalized vector distance < force,
then flattened per strategy: ``closest`` keeps each group's first
(best-ranked) member; ``merge`` folds a group into one synthetic
result — vectors averaged, text values deduped and joined as
"first (b, c)", numbers averaged, booleans majority, geo averaged
(``merge_group.go``). Distinct from the modern ``groupBy`` argument
(reference keeps both; so do we)."""

from __future__ import annotations

from typing import Optional

import numpy as np


def _normalized_distance(a: np.ndarray, b: np.ndarray) -> float:
    """Cosine distance scaled to [0, 1] (reference
    ``vectorizer.NormalizedDistance``)."""
    na = float(np.linalg.norm(a))
    nb = float(np.linalg.norm(b))
    if na == 0.0 or nb == 0.0:
        return 1.0
    sim = float(np.dot(a, b)) / (na * nb)
    return (1.0 - sim) / 2.0


def _merge_text(values: list[str]) -> str:
    seen: dict[str, None] = {}
    for v in values:
        seen.setdefault(v, None)
    uniq = list(seen)
    if len(uniq) == 1:
        return uniq[0]
    return f"{uniq[0]} ({', '.join(uniq[1:])})"


def _merge_values(values: list):
    first = values[0]
    if isinstance(first, bool):
        return sum(bool(v) for v in values) >= len(values) / 2
    if isinstance(first, (int, float)):
        return float(sum(values)) / len(values)
    if isinstance(first, str):
        return _merge_text([str(v) for v in values])
    if isinstance(first, dict) and "latitude" in first:
        return {
            "latitude": sum(v["latitude"] for v in values) / len(values),
            "longitude": sum(v["longitude"] for v in values) / len(values),
        }
    if isinstance(first, list):  # references / arrays concatenate
        out = []
        for v in values:
            out.extend(v if isinstance(v, list) else [v])
        return out
    return first  # unknown type: keep the best-ranked member's value


def legacy_group(hits: list, strategy: str, force: float) -> list:
    """Group ``hits`` (explorer Hit objects, rank order) and flatten.
    Hits without a vector pass through ungrouped (nothing to cluster
    on)."""
    if strategy not in ("closest", "merge"):
        raise ValueError(
            f"unrecognized grouping strategy {strategy!r} "
            "(closest | merge)")
    groups: list[list] = []
    passthrough: list = []
    for h in hits:
        vec = getattr(h.object, "vector", None)
        if vec is None:
            passthrough.append(h)
            continue
        v = np.asarray(vec, np.float32)
        placed = False
        for g in groups:
            if any(_normalized_distance(
                    v, np.asarray(m.object.vector, np.float32)) < force
                   for m in g):
                g.append(h)
                placed = True
                break
        if not placed:
            groups.append([h])

    out = []
    for g in groups:
        if strategy == "closest" or len(g) == 1:
            out.append(g[0])
            continue
        head = g[0]
        merged_props: dict = {}
        names: dict[str, None] = {}
        for m in g:
            for p in m.object.properties:
                names.setdefault(p, None)
        for p in names:
            vals = [m.object.properties[p] for m in g
                    if m.object.properties.get(p) is not None]
            if vals:
                merged_props[p] = _merge_values(vals)
        vecs = [np.asarray(m.object.vector, np.float32) for m in g]
        head.object.properties = merged_props
        head.object.vector = np.mean(np.stack(vecs), axis=0)
        head.additional["group"] = {
            "count": len(g),
            "ids": [m.object.uuid for m in g],
        }
        out.append(head)
    return out + passthrough
