"""graftlint CLI.

Exit codes: 0 clean (baselined/suppressed only), 1 new or stale
violations, 2 usage error / malformed baseline.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from tools.graftlint import baseline as baseline_mod
from tools.graftlint.engine import lint_paths
from tools.graftlint.report import (
    render_json,
    render_sarif,
    render_text,
    summary_line,
)
from tools.graftlint.rules import ALL_RULES, RULE_IDS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST lint guarding the TPU hot path "
                    "(host syncs, recompiles, swallowed errors).")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: the "
                        "repo's weaviate_tpu/, from any cwd)")
    p.add_argument("--format",
                   choices=("text", "json", "sarif", "dot",
                            "errorflow-dot"),
                   default="text",
                   help="text/json: ratcheted report; sarif: SARIF 2.1.0 "
                        "of the NEW violations (CI code annotations); "
                        "dot: the interprocedural lock-order graph "
                        "(graphviz); errorflow-dot: the reply-taint "
                        "flow graph (same shape)")
    p.add_argument("--no-concurrency-cache", action="store_true",
                   help="recompute the whole-program models (concurrency "
                        "AND errorflow) even when source mtimes match "
                        "their caches")
    p.add_argument("--baseline", type=Path,
                   default=baseline_mod.DEFAULT_BASELINE,
                   help="baseline file (default: tools/graftlint/"
                        "baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every violation, ignoring the baseline")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from the current "
                        "violations (deterministic: sorted, path-relative)")
    p.add_argument("--select", metavar="RULES",
                   help="comma-separated rule ids to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--root", type=Path, default=None,
                   help="root for path relativization "
                        "(default: current directory)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print baselined violations")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if not args.paths:
        from tools.graftlint.engine import repo_root

        args.paths = [str(repo_root() / "weaviate_tpu")]

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}\n    {r.description}")
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        # parse-error / unused-suppression are engine-level, always-on ids
        unknown = set(select) - set(RULE_IDS) - {
            "parse-error", "unused-suppression"}
        if unknown:
            print(f"graftlint: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    if args.fix_baseline:
        # the baseline is defined over the full default tree; regenerating
        # it from a rule subset or a sub-path would silently drop every
        # grandfathered entry the partial run didn't see
        if select is not None:
            print("graftlint: --fix-baseline cannot be combined with "
                  "--select (would drop entries for unselected rules)",
                  file=sys.stderr)
            return 2
        if args.baseline == baseline_mod.DEFAULT_BASELINE:
            from tools.graftlint.engine import repo_root

            want = (repo_root() / "weaviate_tpu").resolve()
            got = {Path(p).resolve() for p in args.paths}
            if got != {want}:
                print("graftlint: --fix-baseline with the default baseline "
                      f"must lint exactly {want} (got {sorted(got)}); a "
                      "partial tree would drop unseen grandfathered entries",
                      file=sys.stderr)
                return 2

    result = lint_paths(args.paths, root=args.root, rules=select,
                        concurrency_cache=not args.no_concurrency_cache)

    if args.format == "dot":
        if result.concurrency is None:
            print("graftlint: --format dot needs the concurrency pass "
                  "(do not --select it away)", file=sys.stderr)
            return 2
        print(result.concurrency.to_dot())
        return 0

    if args.format == "errorflow-dot":
        if result.errorflow is None:
            print("graftlint: --format errorflow-dot needs the errorflow "
                  "pass (do not --select it away)", file=sys.stderr)
            return 2
        print(result.errorflow.to_dot())
        return 0

    if args.fix_baseline:
        n = baseline_mod.write(args.baseline, result.violations)
        print(f"graftlint: wrote {n} baseline entries "
              f"({len(result.violations)} violations) to {args.baseline}")
        return 0

    if args.no_baseline:
        from collections import Counter
        new, baselined, stale = result.violations, [], Counter()
    else:
        try:
            budget = baseline_mod.load(args.baseline)
        except baseline_mod.BaselineError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        new, baselined, stale = baseline_mod.match(result.violations, budget)

    if args.format == "json":
        cache_state = (result.concurrency.cache_state
                       if result.concurrency is not None else None)
        ef_cache = (result.errorflow.cache_state
                    if result.errorflow is not None else None)
        print(render_json(new, baselined, stale, len(result.suppressed),
                          result.files_checked, timings=result.timings,
                          concurrency_cache=cache_state,
                          errorflow_cache=ef_cache))
    elif args.format == "sarif":
        print(render_sarif(new, result.files_checked,
                           rules_meta=ALL_RULES))
    else:
        print(render_text(new, baselined, stale, len(result.suppressed),
                          result.files_checked, verbose=args.verbose))

    return 1 if (new or stale) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
