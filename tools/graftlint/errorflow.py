"""Whole-program error-path + deadline-contract analysis.

The second interprocedural pass (the first is ``concurrency.py``, whose
call-graph machinery this reuses). It enforces the two contracts three
PRs in a row had to re-fix by hand:

**Reply taint** (`unchecked-rpc-reply`). Every value returned from
``ClusterNode._call`` / ``_send`` / ``retrying_call``, from a fan-out
result queue, or from a blob-store ``get`` is *tainted*: it may be the
error shape (``{"error": ...}`` / a raised ``KeyError`` for blobs)
rather than data. Taint follows assignment, tuple unpack, queue
put/get (element-wise for tuple payloads), and helper returns. It is
cleared only by a **sanitizer**:

- flowing through ``_expect(reply, key, peer)`` or any registered
  validator (``# graftlint: reply-validator`` on the def line, or
  :func:`register_validator`),
- an explicit error-key read — ``r.get("ok")`` / ``r["error"]`` /
  ``"ok" in r`` style membership tests,
- for blob gets: a lexically enclosing ``try`` whose handlers catch
  the absence (``KeyError`` / ``BlobStoreError`` / broader).

Field access or truthiness-as-success on a tainted reply is the PR 10
bug shape (an error reply read as a verified zero) and is flagged —
SEV_ERROR under ``cluster/``, ``backup/``, ``tiering/``, SEV_WARNING
elsewhere. A *discarded* reply is deliberately not flagged (fire-and-
forget best-effort sends are legitimate; acting on the value without
checking it is not).

**Budget propagation**. The serving ingress set — REST/gRPC handler
methods (classes named ``*API`` under ``weaviate_tpu/api/``),
dispatcher drain (``*Dispatcher`` methods), cycle-runner tasks
(functions registered via ``<cycles>.register("name", fn)``), plus any
def marked ``# graftlint: ingress`` — is computed, then closed over
the call graph. Inside that closure:

- `budget-minted-in-flight` (SEV_WARNING): constructing a fresh
  ``Deadline(...)`` instead of threading ``_op_deadline`` /
  ``RequestContext``. Exempt: the function that *installs* the
  ``RequestContext`` (that IS the ingress mint) and ``_op_deadline``
  itself (the sanctioned fallback mint for non-serving callers).
- `blocking-call-without-deadline` (SEV_ERROR): a blocking primitive
  (``queue.get``, ``Future.result``, bare ``.wait()``, socket
  recv/accept/sendall/connect, blob I/O) with no timeout argument, in
  a function that neither receives a ``deadline``/``timeout`` nor
  touches the deadline machinery — i.e. no clamp exists on any path.

Results are cached through the same ``passcache`` sidecar mechanism as
the concurrency pass (``.errorflow_cache.json``, keyed on
``ERRORFLOW_VERSION`` + source mtimes) and rendered as a reply-taint
flow graph by ``to_dot()`` (same dot shape as the lock-order graph).
See docs/lint.md "Error-path contracts" for the full model and the
triage record of the first tree-wide run.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.graftlint import concurrency as conc
from tools.graftlint.rules import (
    SEV_ERROR,
    SEV_WARNING,
    Violation,
    dotted_name,
)

# bump to invalidate caches when the analysis itself changes
ERRORFLOW_VERSION = 1

UNCHECKED_RPC_REPLY = "unchecked-rpc-reply"
BUDGET_MINTED_IN_FLIGHT = "budget-minted-in-flight"
BLOCKING_CALL_WITHOUT_DEADLINE = "blocking-call-without-deadline"
ERRORFLOW_RULE_IDS = (
    UNCHECKED_RPC_REPLY, BUDGET_MINTED_IN_FLIGHT,
    BLOCKING_CALL_WITHOUT_DEADLINE)

DEFAULT_CACHE = Path(__file__).with_name(".errorflow_cache.json")

# calls whose return value is an RPC reply dict (the taint sources);
# matched by simple name for both attribute (`self.node._call(...)`)
# and bare (`retrying_call(...)`) call forms
REPLY_SOURCE_NAMES = frozenset({"_call", "_send", "retrying_call"})

# reading one of these keys IS the error check — it clears the taint
SANITIZER_KEYS = frozenset({"ok", "error", "status"})

# blob-store access: `<recv>.get(...)` where the receiver name hints at
# a blob store; absence surfaces as an exception, so the sanitizer is a
# lexically enclosing try whose handlers catch it. The heuristic is
# scoped to the modules that actually speak the BlobStore contract —
# "store"-named receivers elsewhere (hfresh's vector store, dict
# registries) have no absence-as-exception semantics to check
BLOB_GET_ATTRS = frozenset({"get", "get_to_file"})
BLOB_IO_ATTRS = frozenset({
    "get", "get_to_file", "put", "put_file", "list", "delete"})
_BLOB_RECV_HINTS = ("store", "blob")
_BLOB_DIRS = (
    "weaviate_tpu/tiering/", "weaviate_tpu/backup/", "weaviate_tpu/storage/")
_BLOB_EXC_NAMES = frozenset({
    "KeyError", "LookupError", "BlobStoreError", "OSError", "Exception",
    "BaseException"})

# per-directory severity escalation: an unverified reply in the
# replication/backup/tiering planes can flip data or drop objects
CRITICAL_REPLY_DIRS = (
    "weaviate_tpu/cluster/", "weaviate_tpu/backup/", "weaviate_tpu/tiering/")

# name-based validators always on: `_expect` raises on error replies,
# `_fan_out` returns only ok()-checked replies
DEFAULT_VALIDATORS = frozenset({"_expect", "_fan_out"})

_VALIDATOR_MARK_RE = re.compile(r"#\s*graftlint:\s*reply-validator\b")
_INGRESS_MARK_RE = re.compile(r"#\s*graftlint:\s*ingress\b")
# on a def whose NAME matches a reply source but whose error channel is
# an exception (it never returns an error-shaped dict) — e.g. the
# external-API `_APIBase._call`, which raises ModuleNotAvailable
_RAISES_MARK_RE = re.compile(r"#\s*graftlint:\s*reply-raises\b")

_registered_validators: Set[str] = set()


def register_validator(name: str) -> None:
    """Register a reply-validator by simple function name (conftest /
    plugin hook). Prefer the in-source ``# graftlint: reply-validator``
    marker for project code — it keeps the contract next to the def."""
    _registered_validators.add(name)


def clear_registered_validators() -> None:
    _registered_validators.clear()


def validator_names() -> frozenset:
    return DEFAULT_VALIDATORS | frozenset(_registered_validators)


# ---------------------------------------------------------------------------
# model


@dataclasses.dataclass
class TaintEdge:
    src: str             # function key or pseudo source node ("rpc:_send")
    dst: str
    path: str
    line: int
    kind: str = "return"  # source | return | queue


class ErrorFlowModel:
    """The computed model: taint flow edges, the ingress closure, and
    the derived findings."""

    def __init__(self):
        self.violations: List[Violation] = []
        self.edges: Dict[Tuple[str, str], TaintEdge] = {}
        self.ingress: Dict[str, str] = {}       # fn key -> root kind
        self.reachable: Set[str] = set()        # ingress closure
        self.tainted_fns: Set[str] = set()      # keys whose return is tainted
        self.cache_state: str = "off"           # off | cold | warm
        self.wall_s: float = 0.0

    def to_dot(self) -> str:
        """The reply-taint flow graph in graphviz dot form — same shape
        as the lock-order graph so the two can sit side by side; nodes
        with unverified-reply findings are red."""
        bad = {f"{v.path}::{v.symbol}" for v in self.violations
               if v.rule == UNCHECKED_RPC_REPLY}
        bad_keys = set()
        nodes: Set[str] = set()
        for (s, d) in self.edges:
            nodes.add(s)
            nodes.add(d)
        out = ["digraph reply_taint {", "  rankdir=LR;",
               '  node [shape=box, fontsize=10];']
        for n in sorted(nodes):
            shape = "ellipse" if ":" in n.split("::")[0] else "box"
            e = self._node_edge(n)
            is_bad = e is not None and f"{e.path}::{_symbol_of(n)}" in bad
            if is_bad:
                bad_keys.add(n)
            color = ' color=red penwidth=2' if is_bad else ""
            out.append(f'  "{n}" [shape={shape}{color}];')
        for (s, d) in sorted(self.edges):
            e = self.edges[(s, d)]
            color = (' color=red penwidth=2'
                     if s in bad_keys or d in bad_keys else "")
            out.append(
                f'  "{s}" -> "{d}" '
                f'[label="{e.path}:{e.line}", fontsize=8{color}];')
        out.append("}")
        return "\n".join(out)

    def _node_edge(self, node: str) -> Optional[TaintEdge]:
        for (s, d), e in self.edges.items():
            if d == node or s == node:
                return e
        return None


def _symbol_of(key: str) -> str:
    return key.split("::", 1)[-1]


# ---------------------------------------------------------------------------
# per-function extraction


@dataclasses.dataclass
class _FnInfo:
    key: str
    module: str
    qual: str
    name: str
    path: str
    line: int
    cls: Optional[str]
    events: List[tuple] = dataclasses.field(default_factory=list)
    calls: List[tuple] = dataclasses.field(default_factory=list)
    cycle_regs: List[tuple] = dataclasses.field(default_factory=list)
    mints: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    blocking: List[Tuple[int, str, bool, str]] = \
        dataclasses.field(default_factory=list)
    installs_ctx: bool = False
    mentions_deadline: bool = False
    is_validator: bool = False
    ingress_marked: bool = False
    raises_marked: bool = False


class _TaintScanner:
    """One top-level function (nested defs and lambdas scanned inline —
    closures run later but share the enclosing taint facts, which is
    exactly what the fan-out worker/drain split needs)."""

    def __init__(self, fm: "conc._FileModel", conc_f, fn: _FnInfo,
                 node: ast.AST):
        self.fm = fm
        self.ctx = fm.ctx
        self.conc_f = conc_f
        self.fn = fn
        self.node = node
        self.param_types: Dict[str, str] = {}
        self._scan_params(node)
        self.scan_body(node.body)

    # -- setup -----------------------------------------------------------

    def _scan_params(self, node) -> None:
        args = (node.args.args + node.args.kwonlyargs
                + node.args.posonlyargs)
        if any(a.arg in ("deadline", "timeout") for a in args):
            self.fn.mentions_deadline = True
        for a in args:
            ann = a.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                self.param_types[a.arg] = ann.value.rsplit(".", 1)[-1]
            else:
                dn = dotted_name(ann) if ann is not None else None
                if dn:
                    self.param_types[a.arg] = dn.rsplit(".", 1)[-1]

    # -- classification helpers -----------------------------------------

    @staticmethod
    def _call_name(call: ast.Call) -> Optional[str]:
        if isinstance(call.func, ast.Attribute):
            return call.func.attr
        if isinstance(call.func, ast.Name):
            return call.func.id
        return None

    def _source_info(self, call: ast.Call) -> Optional[tuple]:
        """(detail, simple-name, receiver-hint) when the call is named
        like a reply source, else None. The hint lets the analyzer
        resolve the actual target and honor ``reply-raises`` markers —
        a receiver it cannot type stays a source (conservative)."""
        name = self._call_name(call)
        if name not in REPLY_SOURCE_NAMES:
            return None
        hint = None
        if isinstance(call.func, ast.Attribute):
            recv = call.func.value
            if isinstance(recv, ast.Name):
                if recv.id == "self" and self.fn.cls:
                    hint = ("self", self.fn.cls)
                elif recv.id in self.param_types:
                    hint = ("cls", self.param_types[recv.id])
        return (self._detail(call), name, hint)

    def _is_blob_recv(self, recv: ast.AST) -> bool:
        if not self.fn.path.startswith(_BLOB_DIRS):
            return False
        dn = dotted_name(recv)
        if dn is None:
            return False
        leaf = dn.rsplit(".", 1)[-1].lower()
        return any(h in leaf for h in _BLOB_RECV_HINTS)

    def _is_blob_call(self, call: ast.Call, attrs: frozenset) -> bool:
        # every BlobStore verb takes the key (or prefix/path) positionally;
        # a zero-arg .get() is a DynamicValue/config read, not blob I/O
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr in attrs
                and bool(call.args)
                and self._is_blob_recv(call.func.value))

    def _is_queue_recv(self, recv: ast.AST) -> bool:
        f = self.conc_f
        if f is None:
            return False
        if isinstance(recv, ast.Name):
            return recv.id in f.local_queues
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and f.cls:
            return recv.attr in self.fm.queue_attrs.get(f.cls, set())
        return False

    def _is_deadline_mint(self, call: ast.Call) -> bool:
        dn = dotted_name(call.func)
        if dn is None:
            return False
        if dn.endswith(".after"):
            dn = dn[:-len(".after")]
        canon = self.fm._canonical(dn) or dn
        return (canon == "Deadline"
                or canon.endswith("resilience.Deadline"))

    def _installs_ctx(self, call: ast.Call) -> bool:
        dn = dotted_name(call.func)
        return dn is not None and dn.rsplit(".", 1)[-1] == "RequestContext"

    def _in_blob_guard(self, call: ast.Call) -> bool:
        """Whether an enclosing try's handlers catch blob absence."""
        for parent, field in self.ctx.ancestry(call):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)) and parent is not self.node:
                # deferred body: runs under whoever invokes the closure
                # (commonly retrying_call with a deadline + retry_on)
                return True
            if isinstance(parent, ast.Try) and field == "body":
                for h in parent.handlers:
                    names = []
                    t = h.type
                    elts = t.elts if isinstance(t, ast.Tuple) else [t]
                    for e in elts:
                        d = dotted_name(e) if e is not None else None
                        if d:
                            names.append(d.rsplit(".", 1)[-1])
                    if t is None or any(n in _BLOB_EXC_NAMES
                                        for n in names):
                        return True
        return False

    def _detail(self, node: ast.AST) -> str:
        dn = dotted_name(getattr(node, "func", node))
        return f"{dn or '<expr>'}(...)" if isinstance(node, ast.Call) \
            else (dn or "<expr>")

    # -- statement walk --------------------------------------------------

    def scan_body(self, stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            self.stmt(st)

    def stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: scanned inline (shared event stream, see class
            # docstring); its params may carry the deadline too
            self._scan_params(st)
            self.scan_body(st.body)
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, ast.Assign):
            self._assign(st.targets, st.value, st.lineno)
            return
        if isinstance(st, ast.AnnAssign) and st.value is not None:
            self._assign([st.target], st.value, st.lineno)
            return
        if isinstance(st, ast.AugAssign):
            self.scan_uses(st.value)
            return
        if isinstance(st, ast.Expr):
            if isinstance(st.value, ast.Call):
                self._bare_call(st.value)
            else:
                self.scan_uses(st.value)
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                spec = self.value_spec(st.value)
                self.fn.events.append(("ret", spec, st.lineno))
            return
        if isinstance(st, (ast.If, ast.While)):
            self.test_uses(st.test)
            self.scan_body(st.body)
            self.scan_body(st.orelse)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            spec = self.value_spec(st.iter)
            if spec[0] == "name":
                self.fn.events.append(
                    ("use", spec[1], "iter", st.lineno,
                     f"for ... in {spec[1]}"))
            elif spec[0] == "source":
                self.fn.events.append(
                    ("usedirect", "iter", st.lineno, spec[1],
                     tuple(spec[1:])))
            self.scan_body(st.body)
            self.scan_body(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self.scan_uses(item.context_expr)
            self.scan_body(st.body)
            return
        if isinstance(st, ast.Try):
            self.scan_body(st.body)
            for h in st.handlers:
                self.scan_body(h.body)
            self.scan_body(st.orelse)
            self.scan_body(st.finalbody)
            return
        if isinstance(st, ast.Assert):
            self.test_uses(st.test)
            return
        if isinstance(st, ast.Raise):
            if st.exc is not None:
                self.scan_uses(st.exc)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self.fn.events.append(("san", t.id, st.lineno))
            return
        # anything else: scan contained expressions generically
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self.scan_uses(child)

    # -- assignment ------------------------------------------------------

    def _assign(self, targets: Sequence[ast.AST], value: ast.AST,
                line: int) -> None:
        spec = self.value_spec(value)
        for t in targets:
            if isinstance(t, ast.Name):
                self.fn.events.append(
                    ("assign", ("name", t.id), spec, line))
            elif isinstance(t, (ast.Tuple, ast.List)):
                names = [e.id if isinstance(e, ast.Name) else None
                         for e in t.elts]
                self.fn.events.append(
                    ("assign", ("names", names), spec, line))
            else:
                # attribute/subscript target: evaluate for side effects
                self.scan_uses(t)

    # -- call handling ---------------------------------------------------

    def _record_call(self, call: ast.Call) -> Optional[tuple]:
        """Shared bookkeeping for every call node: call-graph edge,
        deadline mint, ctx install, blocking site, cycle registration,
        validator-args event. Returns the call descriptor (or None)."""
        func = call.func
        if self._is_deadline_mint(call):
            self.fn.mints.append((call.lineno, self._detail(call)))
            self.fn.mentions_deadline = True
        if self._installs_ctx(call):
            self.fn.installs_ctx = True
        name = self._call_name(call)
        if name in ("_op_deadline", "current_deadline", "retrying_call"):
            self.fn.mentions_deadline = True
        self._record_blocking(call)
        self._record_cycle_reg(call)
        self._record_qput(call)
        desc = self._descriptor(call)
        if desc is not None:
            self.fn.calls.append(desc)
            argnames = [a.id for a in call.args
                        if isinstance(a, ast.Name)]
            if argnames:
                self.fn.events.append(
                    ("args", desc, argnames, call.lineno))
        return desc

    @staticmethod
    def _descriptor(call: ast.Call) -> Optional[tuple]:
        func = call.func
        if isinstance(func, ast.Name):
            return ("name", func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                return ("self", func.attr)
            dn = dotted_name(func)
            if dn is not None:
                return ("dotted", dn)
            return ("attr", func.attr)
        return None

    def _has_timeout_kw(self, call: ast.Call) -> bool:
        return any(kw.arg == "timeout" for kw in call.keywords)

    def _record_blocking(self, call: ast.Call) -> None:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        attr, recv = func.attr, func.value
        line = call.lineno
        detail = f"{dotted_name(recv) or '<expr>'}.{attr}()"
        if attr == "result":
            bounded = bool(call.args) or self._has_timeout_kw(call)
            self.fn.blocking.append((line, "future-result", bounded, detail))
        elif attr == "get" and self._is_queue_recv(recv):
            bounded = bool(call.args) or self._has_timeout_kw(call)
            self.fn.blocking.append((line, "queue-get", bounded, detail))
        elif attr == "wait":
            bounded = bool(call.args) or self._has_timeout_kw(call)
            self.fn.blocking.append((line, "wait", bounded, detail))
        elif attr in ("recv", "accept", "sendall", "connect",
                      "create_connection"):
            bounded = self._has_timeout_kw(call)
            self.fn.blocking.append((line, "socket", bounded, detail))
        elif self._is_blob_call(call, BLOB_IO_ATTRS):
            # blob I/O has no timeout parameter at all; the only clamp
            # is a deadline threaded into the enclosing function
            self.fn.blocking.append((line, "blob-io", False, detail))

    def _pure_spec(self, expr: ast.AST) -> tuple:
        """Side-effect-free value spec (no event emission) for put
        payloads — the generic use-scan records the contained calls."""
        if isinstance(expr, ast.Name):
            return ("name", expr.id)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return ("tuple", [self._pure_spec(e) for e in expr.elts])
        if isinstance(expr, ast.Call):
            si = self._source_info(expr)
            if si is not None:
                return ("source",) + si
        return ("clean",)

    def _record_qput(self, call: ast.Call) -> None:
        func = call.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("put", "put_nowait")
                and self._is_queue_recv(func.value)):
            return
        if not call.args:
            return
        qn = dotted_name(func.value) or "<queue>"
        payload = call.args[0]
        specs = ([self._pure_spec(e) for e in payload.elts]
                 if isinstance(payload, (ast.Tuple, ast.List))
                 else [self._pure_spec(payload)])
        self.fn.events.append(("qput", qn, specs, call.lineno))

    def _record_cycle_reg(self, call: ast.Call) -> None:
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "register"):
            return
        if len(call.args) < 2:
            return
        if not (isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            return
        target = call.args[1]
        if isinstance(target, ast.Name):
            self.fn.cycle_regs.append(("name", target.id))
        elif isinstance(target, ast.Attribute):
            desc = self._descriptor(ast.Call(func=target, args=[],
                                             keywords=[]))
            if desc is not None:
                self.fn.cycle_regs.append(desc)

    def _bare_call(self, call: ast.Call) -> None:
        """Statement-level call: replies may be discarded, blob gets
        must still be guarded, validator args still sanitize."""
        if self._is_blob_call(call, BLOB_GET_ATTRS) \
                and not self._in_blob_guard(call):
            self.fn.events.append(
                ("usedirect", "blob-get", call.lineno, self._detail(call),
                 None))
        self._record_call(call)
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            self.scan_uses(a)

    # -- value specs (assign/return RHS) ---------------------------------

    def value_spec(self, expr: ast.AST) -> tuple:
        if isinstance(expr, ast.Await):
            return self.value_spec(expr.value)
        if isinstance(expr, ast.Name):
            return ("name", expr.id)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return ("tuple", [self.value_spec(e) for e in expr.elts])
        if isinstance(expr, ast.IfExp):
            self.test_uses(expr.test)
            return ("either", self.value_spec(expr.body),
                    self.value_spec(expr.orelse))
        if isinstance(expr, ast.Call):
            return self._call_spec(expr)
        self.scan_uses(expr)
        return ("clean",)

    def _maybe_field_get(self, call: ast.Call) -> bool:
        """Handle the ``<reply>.get("key")`` read pattern (san if the
        key is an error key, use otherwise). True when handled."""
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "get"
                and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
                and not self._is_queue_recv(func.value)
                and not self._is_blob_recv(func.value)):
            return False
        keyname = call.args[0].value
        if isinstance(func.value, ast.Name):
            if keyname in SANITIZER_KEYS:
                self.fn.events.append(
                    ("san", func.value.id, call.lineno))
            else:
                self.fn.events.append(
                    ("use", func.value.id, "field", call.lineno,
                     f"{func.value.id}.get({keyname!r})"))
            self._record_call(call)
            return True
        if isinstance(func.value, ast.Call):
            si = self._source_info(func.value)
            if si is not None:
                self._record_call(func.value)
                if keyname not in SANITIZER_KEYS:
                    self.fn.events.append(
                        ("usedirect", "field", call.lineno, si[0], si))
                return True
        return False

    def _call_spec(self, call: ast.Call) -> tuple:
        if self._maybe_field_get(call):
            for a in list(call.args)[1:] + [kw.value for kw in
                                            call.keywords]:
                self.scan_uses(a)
            return ("clean",)
        si = self._source_info(call)
        if si is not None:
            self._record_call(call)
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                self.scan_uses(a)
            return ("source",) + si
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "get" \
                and self._is_queue_recv(call.func.value):
            self._record_blocking(call)
            qn = dotted_name(call.func.value) or "<queue>"
            return ("qget", qn)
        if self._is_blob_call(call, BLOB_GET_ATTRS):
            if not self._in_blob_guard(call):
                self.fn.events.append(
                    ("usedirect", "blob-get", call.lineno,
                     self._detail(call), None))
            self._record_call(call)
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                self.scan_uses(a)
            return ("clean",)
        desc = self._record_call(call)
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            self.scan_uses(a)
        if desc is not None:
            return ("call", desc)
        return ("clean",)

    # -- generic expression scanning -------------------------------------

    def test_uses(self, test: ast.AST) -> None:
        """If/while/assert condition: bare tainted names and non-
        sanitizer ``.get`` reads here are truthiness-as-success."""
        if isinstance(test, ast.Name):
            self.fn.events.append(
                ("use", test.id, "truthy", test.lineno, f"if {test.id}:"))
            return
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self.test_uses(test.operand)
            return
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                self.test_uses(v)
            return
        if isinstance(test, ast.Call):
            si = self._source_info(test)
            if si is not None:
                self._record_call(test)
                self.fn.events.append(
                    ("usedirect", "truthy", test.lineno, si[0], si))
                return
        self.scan_uses(test)

    def scan_uses(self, expr: ast.AST) -> None:
        """Walk an expression emitting san/use events in source order.
        Nested lambdas are scanned inline (same rationale as nested
        defs)."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Subscript):
                self._subscript(node)
            elif isinstance(node, ast.Call):
                self._use_call(node)
            elif isinstance(node, ast.Compare):
                self._compare(node)

    def _subscript(self, node: ast.Subscript) -> None:
        key = node.slice
        keyname = (key.value
                   if isinstance(key, ast.Constant)
                   and isinstance(key.value, str) else None)
        if isinstance(node.value, ast.Name):
            if keyname in SANITIZER_KEYS:
                self.fn.events.append(
                    ("san", node.value.id, node.lineno))
            else:
                self.fn.events.append(
                    ("use", node.value.id, "field", node.lineno,
                     f"{node.value.id}[{keyname!r}]" if keyname
                     else f"{node.value.id}[...]"))
        elif isinstance(node.value, ast.Call):
            si = self._source_info(node.value)
            if si is not None and keyname not in SANITIZER_KEYS:
                self.fn.events.append(
                    ("usedirect", "field", node.lineno, si[0], si))

    def _use_call(self, call: ast.Call) -> None:
        if self._maybe_field_get(call):
            return
        if self._is_blob_call(call, BLOB_GET_ATTRS) \
                and not self._in_blob_guard(call):
            self.fn.events.append(
                ("usedirect", "blob-get", call.lineno, self._detail(call),
                 None))
        self._record_call(call)

    def _compare(self, node: ast.Compare) -> None:
        # `"digests" in r` / `"x" not in r`: an explicit presence check —
        # the code has a branch for the missing-key case
        if len(node.ops) == 1 and isinstance(node.ops[0],
                                             (ast.In, ast.NotIn)):
            right = node.comparators[0]
            if isinstance(right, ast.Name):
                self.fn.events.append(("san", right.id, node.lineno))


# ---------------------------------------------------------------------------
# global analysis


class Analyzer:
    """Builds per-function taint summaries on top of the concurrency
    pass's file models + call resolution, then runs the fixpoint and
    derives findings."""

    def __init__(self, contexts: Dict[str, "FileContext"]):
        self.conc = conc.Analyzer(contexts)
        self.fns: Dict[str, _FnInfo] = {}
        self._fm_of: Dict[str, conc._FileModel] = {}
        self._cf_of: Dict[str, object] = {}
        # simple single-inheritance view for method lookup through
        # project base classes (the reply-raises marker on a base's
        # `_call` must cover every subclass receiver)
        self.class_bases: Dict[Tuple[str, str], List[str]] = {}
        self.class_sites: Dict[str, Set[str]] = {}
        for rel, fm in self.conc.files.items():
            self._extract_file(fm)

    # -- extraction ------------------------------------------------------

    def _extract_file(self, fm: "conc._FileModel") -> None:
        ctx = fm.ctx
        vnames = validator_names()
        for st in ctx.tree.body:
            if isinstance(st, ast.ClassDef):
                bases = []
                for b in st.bases:
                    dn = dotted_name(b)
                    if dn:
                        bases.append(dn.rsplit(".", 1)[-1])
                self.class_bases[(fm.module, st.name)] = bases
                self.class_sites.setdefault(st.name, set()).add(fm.module)
        for node, qual, cls in self._iter_defs(ctx):
            key = f"{fm.module}::{qual}"
            defline = ctx.lines[node.lineno - 1] \
                if node.lineno <= len(ctx.lines) else ""
            fn = _FnInfo(
                key=key, module=fm.module, qual=qual, name=node.name,
                path=fm.rel_path, line=node.lineno, cls=cls,
                is_validator=(node.name in vnames
                              or bool(_VALIDATOR_MARK_RE.search(defline))),
                ingress_marked=bool(_INGRESS_MARK_RE.search(defline)),
                raises_marked=bool(_RAISES_MARK_RE.search(defline)))
            conc_f = fm.funcs.get(qual)
            _TaintScanner(fm, conc_f, fn, node)
            self.fns[key] = fn
            self._fm_of[key] = fm
            self._cf_of[key] = conc_f

    @staticmethod
    def _iter_defs(ctx):
        """Top-level defs + methods (nested defs are scanned inline by
        the owner's scanner, matching closure semantics)."""
        for st in ctx.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield st, st.name, None
            elif isinstance(st, ast.ClassDef):
                for sub in st.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        yield sub, f"{st.name}.{sub.name}", st.name

    # -- resolution ------------------------------------------------------

    def resolve(self, key: str, desc: tuple) -> List[str]:
        fm = self._fm_of.get(key)
        if fm is None:
            return []
        keys = self.conc.resolve_call(fm, self._cf_of.get(key), desc)
        return [k for k in keys if k in self.fns]

    def _is_validator_call(self, key: str, desc: tuple) -> bool:
        simple = str(desc[-1]).rsplit(".", 1)[-1]
        if simple in validator_names():
            return True
        return any(self.fns[k].is_validator
                   for k in self.resolve(key, desc))

    def _lookup_method(self, module: str, cls: str, name: str,
                       depth: int = 0) -> Optional[str]:
        """Resolve ``cls.name`` through the project class hierarchy
        (same module first, then uniquely-named classes elsewhere)."""
        if depth > 8:
            return None
        k = f"{module}::{cls}.{name}"
        if k in self.fns:
            return k
        for base in self.class_bases.get((module, cls), ()):
            r = self._lookup_method(module, base, name, depth + 1)
            if r is not None:
                return r
            for m in self.class_sites.get(base, ()):
                if m != module:
                    r = self._lookup_method(m, base, name, depth + 1)
                    if r is not None:
                        return r
        return None

    def _source_is_reply(self, key: str, name: str,
                         hint: Optional[tuple]) -> bool:
        """Whether a source-named call actually yields a reply-shaped
        value. False only when the receiver resolves to a function
        marked ``# graftlint: reply-raises`` (error channel is an
        exception); unresolvable receivers stay sources."""
        if hint is None:
            return True
        fn = self.fns.get(key)
        if fn is None:
            return True
        target = self._lookup_method(fn.module, hint[1], name)
        if target is None and hint[0] == "cls":
            mods = self.class_sites.get(hint[1], set())
            if len(mods) == 1:
                target = self._lookup_method(
                    next(iter(mods)), hint[1], name)
        if target is not None and self.fns[target].raises_marked:
            return False
        return True

    # -- taint replay ----------------------------------------------------

    def _spec_taint(self, key: str, spec: tuple, tainted: Dict[str, str],
                    qtaint: Dict[str, Set[int]],
                    returns_tainted: Set[str]) -> Optional[str]:
        kind = spec[0]
        if kind == "source":
            if self._source_is_reply(key, spec[2], spec[3]):
                return spec[1]
            return None
        if kind == "name":
            return tainted.get(spec[1])
        if kind == "qget":
            return f"reply from queue {spec[1]}" \
                if qtaint.get(spec[1]) else None
        if kind == "call":
            desc = spec[1]
            if self._is_validator_call(key, desc):
                return None
            for k in self.resolve(key, desc):
                if k in returns_tainted:
                    return f"return of {_symbol_of(k)}"
            return None
        if kind == "either":
            return (self._spec_taint(key, spec[1], tainted, qtaint,
                                     returns_tainted)
                    or self._spec_taint(key, spec[2], tainted, qtaint,
                                        returns_tainted))
        if kind == "tuple":
            for s in spec[1]:
                origin = self._spec_taint(key, s, tainted, qtaint,
                                          returns_tainted)
                if origin:
                    return origin
            return None
        return None

    def _replay(self, fn: _FnInfo, returns_tainted: Set[str],
                emit: Optional[list]) -> bool:
        """Interpret the event stream; returns whether the function's
        return value is tainted. ``emit`` collects (event, origin)
        violations on the final pass."""
        key = fn.key
        tainted: Dict[str, str] = {}
        qtaint: Dict[str, Set[int]] = {}
        rt = False
        for ev in fn.events:
            k = ev[0]
            if k == "san":
                tainted.pop(ev[1], None)
            elif k == "args":
                _, desc, names, _line = ev
                if self._is_validator_call(key, desc):
                    for n in names:
                        tainted.pop(n, None)
            elif k == "use":
                _, name, ukind, line, detail = ev
                origin = tainted.get(name)
                if origin and emit is not None:
                    emit.append((fn, ukind, line, detail, origin))
            elif k == "usedirect":
                _, ukind, line, detail, srcinfo = ev
                if srcinfo is not None and not self._source_is_reply(
                        key, srcinfo[1], srcinfo[2]):
                    continue
                if emit is not None:
                    emit.append((fn, ukind, line, detail, detail))
            elif k == "assign":
                _, tgt, spec, line = ev
                self._do_assign(fn, tgt, spec, line, tainted, qtaint,
                                returns_tainted)
            elif k == "qput":
                _, qn, specs, _line = ev
                pos = qtaint.setdefault(qn, set())
                for i, s in enumerate(specs):
                    if self._spec_taint(key, s, tainted, qtaint,
                                        returns_tainted):
                        pos.add(i)
            elif k == "ret":
                _, spec, _line = ev
                if self._spec_taint(key, spec, tainted, qtaint,
                                    returns_tainted):
                    rt = True
        return rt

    def _do_assign(self, fn: _FnInfo, tgt: tuple, spec: tuple, line: int,
                   tainted: Dict[str, str], qtaint: Dict[str, Set[int]],
                   returns_tainted: Set[str]) -> None:
        key = fn.key
        origin = self._spec_taint(key, spec, tainted, qtaint,
                                  returns_tainted)
        if tgt[0] == "name":
            if origin:
                tainted[tgt[1]] = origin
            else:
                tainted.pop(tgt[1], None)
            return
        names = tgt[1]
        if spec[0] == "qget" and qtaint.get(spec[1]):
            # element-wise: only the positions that received a tainted
            # payload element at put-time are tainted at get-time
            pos = qtaint[spec[1]]
            for i, n in enumerate(names):
                if n is None:
                    continue
                if i in pos:
                    tainted[n] = f"reply from queue {spec[1]}"
                else:
                    tainted.pop(n, None)
            return
        if spec[0] == "tuple":
            for i, n in enumerate(names):
                if n is None:
                    continue
                s = spec[1][i] if i < len(spec[1]) else ("clean",)
                o = self._spec_taint(key, s, tainted, qtaint,
                                     returns_tainted)
                if o:
                    tainted[n] = o
                else:
                    tainted.pop(n, None)
            return
        for n in names:
            if n is None:
                continue
            if origin:
                tainted[n] = origin
            else:
                tainted.pop(n, None)

    # -- ingress + reachability ------------------------------------------

    def _ingress_roots(self) -> Dict[str, str]:
        roots: Dict[str, str] = {}
        for key, fn in self.fns.items():
            if fn.ingress_marked:
                roots[key] = "marked"
                continue
            if (fn.module.startswith("weaviate_tpu.api.")
                    and (fn.cls is None or fn.cls.endswith("API"))):
                roots[key] = "api"
            elif fn.cls is not None and fn.cls.endswith("Dispatcher"):
                roots[key] = "dispatcher"
        for key, fn in self.fns.items():
            for desc in fn.cycle_regs:
                for k in self.resolve(key, desc):
                    roots.setdefault(k, "cycle")
        return roots

    def _reachable(self, roots: Dict[str, str]) -> Set[str]:
        seen = set(roots)
        work = list(roots)
        while work:
            cur = work.pop()
            fn = self.fns.get(cur)
            if fn is None:
                continue
            for desc in fn.calls:
                for k in self.resolve(cur, desc):
                    if k not in seen:
                        seen.add(k)
                        work.append(k)
        return seen

    # -- findings --------------------------------------------------------

    def run(self) -> ErrorFlowModel:
        model = ErrorFlowModel()

        # returns-tainted fixpoint over helper returns
        returns_tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for key, fn in self.fns.items():
                if key in returns_tainted:
                    continue
                if self._replay(fn, returns_tainted, emit=None):
                    returns_tainted.add(key)
                    changed = True
        model.tainted_fns = set(returns_tainted)

        # final replay, collecting reply-taint findings + flow edges
        for key, fn in self.fns.items():
            found: list = []
            self._replay(fn, returns_tainted, emit=found)
            for (f, ukind, line, detail, origin) in found:
                model.violations.append(self._reply_violation(
                    f, ukind, line, detail, origin))
            self._flow_edges(model, fn, returns_tainted)

        roots = self._ingress_roots()
        model.ingress = roots
        reach = self._reachable(roots)
        model.reachable = reach

        for key in sorted(reach):
            fn = self.fns.get(key)
            if fn is None:
                continue
            self._budget_findings(model, fn)

        model.violations.sort(
            key=lambda v: (v.path, v.line, v.col, v.rule))
        return model

    def _reply_violation(self, fn: _FnInfo, ukind: str, line: int,
                         detail: str, origin: str) -> Violation:
        sev = SEV_ERROR if any(fn.path.startswith(d)
                               for d in CRITICAL_REPLY_DIRS) \
            else SEV_WARNING
        if ukind == "blob-get":
            msg = (f"blob-store read {detail} outside a KeyError/"
                   "BlobStoreError handler — absence surfaces as a raw "
                   "exception far from the call; wrap in try/except or "
                   "route through a registered validator")
        elif ukind == "truthy":
            msg = (f"truthiness of an unverified RPC reply ({origin}) "
                   "used as a success signal — an error reply "
                   "{'error': ...} is truthy (and a missing-key .get() "
                   "on it reads as empty); check _expect()/an error key "
                   "first (the PR 10 verified-zero bug shape)")
        elif ukind == "iter":
            msg = (f"iterating an unverified RPC reply ({origin}) — an "
                   "error reply iterates as its keys; check _expect()/"
                   "an error key first")
        else:
            msg = (f"field {detail} read from an unverified RPC reply "
                   f"({origin}) — an error reply {{'error': ...}} has "
                   "no data keys, so this reads as missing/zero; route "
                   "through _expect() or an explicit error-key check")
        fm = self._fm_of[fn.key]
        return Violation(
            rule=UNCHECKED_RPC_REPLY, path=fn.path, line=line, col=0,
            severity=sev, message=msg, symbol=fn.qual,
            snippet=fm.ctx.line_snippet(line))

    def _budget_findings(self, model: ErrorFlowModel, fn: _FnInfo) -> None:
        fm = self._fm_of[fn.key]
        if fn.mints and not fn.installs_ctx \
                and fn.name != "_op_deadline":
            for (line, detail) in fn.mints:
                model.violations.append(Violation(
                    rule=BUDGET_MINTED_IN_FLIGHT, path=fn.path,
                    line=line, col=0, severity=SEV_WARNING,
                    message=(
                        f"fresh {detail} minted on a serving path "
                        "(reachable from the ingress set) — thread the "
                        "ingress budget via RequestContext/"
                        "_op_deadline() instead; a leg that mints its "
                        "own budget outlives the request that paid for "
                        "it (the PR 16 backup-leg bug shape)"),
                    symbol=fn.qual,
                    snippet=fm.ctx.line_snippet(line)))
        if fn.mentions_deadline:
            return
        for (line, cat, bounded, detail) in fn.blocking:
            if bounded:
                continue
            model.violations.append(Violation(
                rule=BLOCKING_CALL_WITHOUT_DEADLINE, path=fn.path,
                line=line, col=0, severity=SEV_ERROR,
                message=(
                    f"unbounded {cat} {detail} reachable from the "
                    "serving ingress set with no deadline clamp on any "
                    "path — pass timeout=deadline.per_attempt(...) or "
                    "thread a deadline/timeout parameter into "
                    f"{fn.name}()"),
                symbol=fn.qual,
                snippet=fm.ctx.line_snippet(line)))

    def _flow_edges(self, model: ErrorFlowModel, fn: _FnInfo,
                    returns_tainted: Set[str]) -> None:
        """Taint flow graph: pseudo source nodes -> consuming functions,
        plus callee -> caller edges where taint crosses a return."""
        key = fn.key
        for ev in fn.events:
            if ev[0] == "assign":
                self._edge_from_spec(model, fn, ev[2], ev[3],
                                     returns_tainted)
            elif ev[0] == "ret":
                self._edge_from_spec(model, fn, ev[1], ev[2],
                                     returns_tainted)

    def _edge_from_spec(self, model: ErrorFlowModel, fn: _FnInfo,
                        spec: tuple, line: int,
                        returns_tainted: Set[str]) -> None:
        kind = spec[0]
        if kind == "source":
            if not self._source_is_reply(fn.key, spec[2], spec[3]):
                return
            name = spec[1].split("(", 1)[0].rsplit(".", 1)[-1]
            src = f"rpc:{name}"
            model.edges.setdefault((src, fn.key), TaintEdge(
                src=src, dst=fn.key, path=fn.path, line=line,
                kind="source"))
        elif kind == "qget":
            src = f"queue:{spec[1]}"
            model.edges.setdefault((src, fn.key), TaintEdge(
                src=src, dst=fn.key, path=fn.path, line=line,
                kind="queue"))
        elif kind == "call":
            for k in self.resolve(fn.key, spec[1]):
                if k in returns_tainted:
                    model.edges.setdefault((k, fn.key), TaintEdge(
                        src=k, dst=fn.key, path=fn.path, line=line,
                        kind="return"))
        elif kind in ("tuple", "either"):
            for s in spec[1:] if kind == "either" else spec[1]:
                self._edge_from_spec(model, fn, s, line, returns_tainted)


# ---------------------------------------------------------------------------
# entry points + cache


def analyze_contexts(contexts: Dict[str, "FileContext"]) -> ErrorFlowModel:
    """Run the whole-program error-flow analysis over pre-built
    FileContexts."""
    return Analyzer(contexts).run()


def analyze_sources(sources: Dict[str, str]) -> ErrorFlowModel:
    """Test/utility entry: analyze raw sources keyed by rel path."""
    from tools.graftlint.engine import FileContext
    return analyze_contexts(
        {rel: FileContext(src, rel) for rel, src in sources.items()})


def check_contexts(contexts: Dict[str, "FileContext"],
                   meta: Optional[Dict[str, Tuple[int, int]]] = None,
                   cache_path: Optional[Path] = None) -> ErrorFlowModel:
    """Analysis behind the shared ``passcache`` sidecar — one cache
    invalidation path for both whole-program passes."""
    import time as _time

    from tools.graftlint import passcache

    t0 = _time.perf_counter()
    data = passcache.load(cache_path, ERRORFLOW_VERSION, meta)
    if data is not None:
        try:
            model = ErrorFlowModel()
            model.cache_state = "warm"
            for d in data["violations"]:
                model.violations.append(Violation(**d))
            for d in data["edges"]:
                e = TaintEdge(**d)
                model.edges[(e.src, e.dst)] = e
            model.ingress = dict(data["ingress"])
            model.reachable = set(data["reachable"])
            model.tainted_fns = set(data["tainted_fns"])
            model.wall_s = _time.perf_counter() - t0
            return model
        except (ValueError, KeyError, TypeError):
            pass  # malformed payload: recompute and overwrite
    model = analyze_contexts(contexts)
    model.cache_state = "cold" if cache_path is not None else "off"
    model.wall_s = _time.perf_counter() - t0
    from tools.graftlint import passcache as _pc
    _pc.store(cache_path, ERRORFLOW_VERSION, meta, {
        "violations": [v.to_dict() for v in model.violations],
        "edges": [dataclasses.asdict(e)
                  for _, e in sorted(model.edges.items())],
        "ingress": dict(sorted(model.ingress.items())),
        "reachable": sorted(model.reachable),
        "tainted_fns": sorted(model.tainted_fns),
    })
    return model
