"""Baseline (ratchet) persistence and matching.

The baseline is a committed JSON multiset of violation fingerprints.
Matching is by ``(rule, path, symbol, snippet)`` with a count — line
numbers are deliberately excluded so edits elsewhere in a file do not
churn the file. The check ratchets in both directions:

* a current violation with no remaining baseline budget is **new** -> fail;
* a baseline entry with no matching current violation is **stale** -> fail
  (whoever fixed it must also shrink the baseline via ``--fix-baseline``,
  keeping the committed count an honest upper bound).

``--fix-baseline`` regenerates the file deterministically (entries sorted,
paths posix-relative) so diffs stay reviewable.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from tools.graftlint.rules import Violation

BASELINE_VERSION = 1
DEFAULT_BASELINE = Path(__file__).with_name("baseline.json")

_ENTRY_KEYS = {"rule", "path", "symbol", "snippet", "count"}


class BaselineError(ValueError):
    """Malformed baseline file — refuse to guess, fail the run."""


Fingerprint = Tuple[str, str, str, str]


def _entry_fingerprint(e: dict) -> Fingerprint:
    return (e["rule"], e["path"], e["symbol"], e["snippet"])


def load(path: Path) -> Counter:
    """Load + validate; returns a Counter of fingerprints. A missing file
    is an empty baseline (the zero-violation end state deletes it)."""
    if not path.exists():
        return Counter()
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except json.JSONDecodeError as e:
        raise BaselineError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(data, dict):
        raise BaselineError(f"{path}: top level must be an object")
    if data.get("version") != BASELINE_VERSION:
        raise BaselineError(
            f"{path}: unsupported version {data.get('version')!r} "
            f"(expected {BASELINE_VERSION})")
    entries = data.get("entries")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: 'entries' must be a list")
    budget: Counter = Counter()
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or set(e) != _ENTRY_KEYS:
            raise BaselineError(
                f"{path}: entry {i} must have exactly keys "
                f"{sorted(_ENTRY_KEYS)}")
        if not all(isinstance(e[k], str) for k in
                   ("rule", "path", "symbol", "snippet")):
            raise BaselineError(f"{path}: entry {i} has non-string fields")
        if not isinstance(e["count"], int) or e["count"] < 1:
            raise BaselineError(f"{path}: entry {i} count must be int >= 1")
        fp = _entry_fingerprint(e)
        if fp in budget:
            raise BaselineError(
                f"{path}: duplicate entry {i} for {e['path']} [{e['rule']}] "
                "— merge counts")
        budget[fp] = e["count"]
    return budget


def match(violations: Sequence[Violation],
          budget: Counter) -> Tuple[List[Violation], List[Violation], Counter]:
    """Split current violations into (new, baselined); the third element
    is the stale remainder — baseline budget nothing matched."""
    remaining = Counter(budget)
    new: List[Violation] = []
    baselined: List[Violation] = []
    for v in violations:
        fp = v.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            baselined.append(v)
        else:
            new.append(v)
    stale = Counter({fp: n for fp, n in remaining.items() if n > 0})
    return new, baselined, stale


def write(path: Path, violations: Sequence[Violation]) -> int:
    """Regenerate the baseline from the current violation set. Returns
    the number of (merged) entries written; an empty set deletes the
    file so the end state of the ratchet is no baseline at all."""
    counts: Counter = Counter(v.fingerprint() for v in violations)
    if not counts:
        if path.exists():
            path.unlink()
        return 0
    entries = [
        {"rule": fp[0], "path": fp[1], "symbol": fp[2], "snippet": fp[3],
         "count": n}
        for fp, n in sorted(counts.items())
    ]
    payload: Dict = {
        "version": BASELINE_VERSION,
        "comment": (
            "Grandfathered graftlint violations. Do not add entries by "
            "hand; fix the code, or run --fix-baseline and justify the "
            "diff in review."
        ),
        "entries": entries,
    }
    path.write_text(json.dumps(payload, indent=2, sort_keys=False) + "\n",
                    encoding="utf-8")
    return len(entries)
