"""Text / JSON reporters + the one-line summary used for BENCH-style
tracking (violation counts over time)."""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Optional, Sequence

from tools.graftlint.rules import Violation

Fingerprint = tuple


def _fmt(v: Violation) -> str:
    return (f"{v.path}:{v.line}:{v.col + 1}: [{v.rule}] {v.severity}: "
            f"{v.message}\n    {v.snippet}")


def render_text(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Counter, suppressed_count: int, files_checked: int,
                verbose: bool = False) -> str:
    out: List[str] = []
    for v in new:
        out.append(_fmt(v))
    if verbose and baselined:
        out.append("")
        out.append("baselined (grandfathered — burn these down):")
        for v in baselined:
            out.append("  " + _fmt(v).replace("\n", "\n  "))
    for fp, n in sorted(stale.items()):
        out.append(
            f"{fp[1]}: [{fp[0]}] stale-baseline: {n} grandfathered "
            f"violation(s) in {fp[2]} no longer occur — run --fix-baseline "
            f"to ratchet down\n    {fp[3]}")
    out.append(summary_line(new, baselined, stale, suppressed_count,
                            files_checked))
    return "\n".join(out)


def summary_line(new: Sequence[Violation], baselined: Sequence[Violation],
                 stale: Counter, suppressed_count: int,
                 files_checked: int) -> str:
    status = "FAIL" if (new or stale) else "OK"
    n_stale = sum(stale.values())
    return (f"graftlint: {status} — {files_checked} files, "
            f"{len(new)} new, {len(baselined)} baselined, "
            f"{suppressed_count} suppressed, {n_stale} stale")


def render_json(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Counter, suppressed_count: int,
                files_checked: int,
                timings: Optional[dict] = None,
                concurrency_cache: Optional[str] = None,
                errorflow_cache: Optional[str] = None) -> str:
    doc = {
        "summary": {
            "status": "fail" if (new or stale) else "ok",
            "files_checked": files_checked,
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": suppressed_count,
            "stale": sum(stale.values()),
        },
        "violations": [v.to_dict() for v in new],
        "baselined": [v.to_dict() for v in baselined],
        "stale": [
            {"rule": fp[0], "path": fp[1], "symbol": fp[2],
             "snippet": fp[3], "count": n}
            for fp, n in sorted(stale.items())
        ],
    }
    if timings is not None:
        # wall-time per phase so tier-1 budget creep is visible in the
        # same artifact CI already collects
        doc["summary"]["timings"] = dict(timings)
    if concurrency_cache is not None:
        doc["summary"]["concurrency_cache"] = concurrency_cache
    if errorflow_cache is not None:
        doc["summary"]["errorflow_cache"] = errorflow_cache
    return json.dumps(doc, indent=2)


# ---------------------------------------------------------------------------
# SARIF 2.1.0 — findings render as code annotations in CI


_SARIF_LEVEL = {"warning": "warning", "error": "error", "critical": "error"}


def render_sarif(new: Sequence[Violation], files_checked: int,
                 rules_meta: Sequence = ()) -> str:
    """Minimal-but-valid SARIF 2.1.0 log of the NEW violations (the
    baseline/suppression pipeline has already run; grandfathered and
    annotated findings do not become annotations)."""
    rule_ids = sorted({v.rule for v in new})
    meta_by_id = {r.id: r for r in rules_meta}
    rules = []
    for rid in rule_ids:
        r = meta_by_id.get(rid)
        rules.append({
            "id": rid,
            "shortDescription": {
                "text": getattr(r, "description", rid) or rid},
            "helpUri": "docs/lint.md",
        })
    results = []
    for v in new:
        results.append({
            "ruleId": v.rule,
            "level": _SARIF_LEVEL.get(v.severity, "warning"),
            "message": {"text": v.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": v.path},
                    "region": {
                        "startLine": v.line,
                        "startColumn": v.col + 1,
                        "snippet": {"text": v.snippet},
                    },
                },
                "logicalLocations": [{"fullyQualifiedName": v.symbol}],
            }],
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "docs/lint.md",
                "rules": rules,
            }},
            "results": results,
            "properties": {"files_checked": files_checked},
        }],
    }
    return json.dumps(doc, indent=2)
