"""Interprocedural concurrency contract checker.

PR 7 found a real process deadlock (two threads interleaving per-device
enqueues of collective SPMD programs) only by reproducing it live, and
fixed it with a convention — the process-wide ``mesh_dispatch_lock`` —
that nothing enforced. Meanwhile the tree has grown 35+ locks across
``cluster/``, ``serving/``, ``tiering/`` and ``storage/`` with no tool
that can see an ordering cycle. This module is that tool: a
whole-program pass (the rest of graftlint is per-file) that builds

1. a **lock model** — every ``threading.Lock/RLock/Condition`` attribute,
   module global, and function local, identified by owner (module, class,
   name). ``Condition(self._lock)`` aliases to the underlying lock;
   RLock/Condition are reentrant, Lock is not.
2. a **call graph** — module-level functions, methods and nested defs,
   with calls resolved through each file's import table, ``self.``
   dispatch, class instantiation, and (capped, last-resort) by-name
   matching.
3. the **lock-order graph** — which locks can be held when each other
   lock is acquired, propagated through calls: ``f`` holding ``L`` that
   calls ``g`` contributes an edge ``L -> M`` for every lock ``M`` that
   ``g`` transitively acquires.

Three whole-program rules are derived from the model (registered in
``rules.py``; reported, suppressed and baselined exactly like per-file
rules):

- ``lock-order-cycle`` (error): a cycle in the lock-order graph is a
  potential deadlock — two threads entering the cycle from different
  edges wedge forever. Includes self-cycles on non-reentrant locks
  (direct re-acquisition, or a call chain that re-enters a module-global
  ``Lock``).
- ``blocking-under-lock`` (warning): a blocking operation — RPC send,
  ``time.sleep``/retry backoff, ``Future.result()``, ``queue.get()``,
  ``Event``/``Condition.wait`` on a foreign lock, or a *callee's* device
  dispatch — reachable while a lock is held. This generalizes the
  per-file ``lock-across-device-call`` rule interprocedurally (direct
  dispatch under a lock stays with the old rule; this one follows
  calls).
- ``unlocked-collective-dispatch`` (error): a collective-bearing mesh
  program (a jitted callable whose traced body contains
  ``all_gather``/``psum``/``pmin``/... or the cross-shard merge)
  dispatched on a path that can be reached without
  ``mesh_dispatch_lock`` held — the exact PR 7 deadlock, now
  un-regressable.

The pass reuses the per-file ``FileContext`` objects the engine already
built (no second parse) and caches its findings keyed on source mtimes
(``.concurrency_cache.json`` next to this file) so a warm tier-1 run
pays only the stat calls.

The static model is validated against reality by the runtime witness
(``weaviate_tpu/utils/lockwitness.py``): the instrumented locks record
the dynamic held-set at every acquire, and the witness's observed-order
graph must embed into this module's static graph.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.graftlint.rules import (
    SEV_ERROR,
    SEV_WARNING,
    Violation,
    dotted_name,
    is_dispatch_call,
)

# bump to invalidate caches when the analysis itself changes
CONCURRENCY_VERSION = 1

LOCK_ORDER_CYCLE = "lock-order-cycle"
BLOCKING_UNDER_LOCK = "blocking-under-lock"
UNLOCKED_COLLECTIVE = "unlocked-collective-dispatch"
CONCURRENCY_RULE_IDS = (
    LOCK_ORDER_CYCLE, BLOCKING_UNDER_LOCK, UNLOCKED_COLLECTIVE)

DEFAULT_CACHE = Path(__file__).with_name(".concurrency_cache.json")

# the one process-wide collective-dispatch order lock (PR 7)
MESH_LOCK_ID = "weaviate_tpu.parallel.sharded_search._DISPATCH_LOCK"

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}

# cross-device rendezvous primitives: a jitted program containing one of
# these deadlocks if two programs' per-device enqueues interleave
_COLLECTIVE_NAMES = frozenset({
    "all_gather", "psum", "pmin", "pmax", "all_to_all", "ppermute",
    "pmean", "merge_across_shards",
})

# attribute-call names treated as blocking RPC/socket sends
_RPC_NAMES = frozenset({
    "_call", "urlopen", "sendall", "recv", "connect", "accept",
    "create_connection", "getresponse",
})

_QUEUE_CTORS = frozenset({
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "multiprocessing.Queue",
})

# attribute names never resolved by-name (enormous fan-out and/or
# always stdlib/container methods); blocking-relevant ones (.get,
# .result, .wait, .acquire) are classified directly instead
_NO_BYNAME = frozenset({
    "get", "put", "items", "keys", "values", "append", "add", "pop",
    "close", "update", "copy", "join", "split", "strip", "read",
    "write", "open", "encode", "decode", "format", "setdefault",
    "extend", "insert", "remove", "discard", "clear", "sort", "index",
    "count", "group", "match", "search", "sub", "info", "debug",
    "warning", "error", "exception", "log", "inc", "dec", "observe",
    "set", "submit", "done", "cancel", "start", "is_set", "locked",
    "acquire", "release", "wait", "notify", "notify_all", "result",
    "item", "tolist", "astype", "reshape", "exists", "mkdir", "stat",
    "resolve", "unlink", "touch", "flush", "seek", "tell", "fileno",
    "sleep", "send",
})

_BYNAME_CAP = 3  # by-name attr resolution only when <= this many defs
_CHAIN_MAX = 4   # call-chain depth kept for messages


# ---------------------------------------------------------------------------
# model dataclasses


@dataclasses.dataclass
class LockDef:
    id: str
    kind: str            # lock | rlock | condition
    path: str
    line: int
    alias_of: Optional[str] = None  # Condition(self._lock) -> that lock

    @property
    def reentrant(self) -> bool:
        return self.kind in ("rlock", "condition")


@dataclasses.dataclass
class _Event:
    kind: str            # acquire | call | blocking | collective
    line: int
    held: Tuple[str, ...]          # lock ids held at this point
    lock: Optional[str] = None     # acquire: lock id
    callee: Optional[tuple] = None  # call: descriptor
    detail: str = ""
    category: str = ""             # blocking: sleep|future-result|...
    direct_receiver: str = ""      # acquire: source receiver expr


@dataclasses.dataclass
class _Func:
    key: str             # "module::qualname"
    module: str
    qual: str            # in-file qualname
    path: str
    line: int
    cls: Optional[str]
    events: List[_Event] = dataclasses.field(default_factory=list)
    local_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    local_queues: Set[str] = dataclasses.field(default_factory=set)
    jit_locals: Set[str] = dataclasses.field(default_factory=set)
    direct_dispatch: Optional[int] = None  # line of a direct device dispatch
    jitted: bool = False  # body executes at trace time, not dispatch time


@dataclasses.dataclass
class Edge:
    src: str
    dst: str
    path: str
    line: int
    func: str            # in-file qualname where the edge was observed
    via: str = ""        # callee chain note for propagated edges


class ConcurrencyModel:
    """The computed whole-program model: lock defs, call graph summary,
    lock-order edges, and the derived findings."""

    def __init__(self):
        self.locks: Dict[str, LockDef] = {}
        self.edges: Dict[Tuple[str, str], Edge] = {}
        self.violations: List[Violation] = []
        self.cache_state: str = "off"   # off | cold | warm
        self.wall_s: float = 0.0

    def to_dot(self) -> str:
        """The lock-order graph in graphviz dot form; cycle edges red."""
        cyc_edges = set()
        for scc in _sccs({(e.src, e.dst) for e in self.edges.values()}):
            if len(scc) > 1:
                for (s, d) in self.edges:
                    if s in scc and d in scc:
                        cyc_edges.add((s, d))
        for (s, d) in self.edges:
            if s == d:
                cyc_edges.add((s, d))
        out = ["digraph lock_order {", "  rankdir=LR;",
               '  node [shape=box, fontsize=10];']
        for lid in sorted(self.locks):
            ld = self.locks[lid]
            shape = "ellipse" if ld.reentrant else "box"
            out.append(f'  "{lid}" [shape={shape}];')
        for (s, d) in sorted(self.edges):
            e = self.edges[(s, d)]
            color = ' color=red penwidth=2' if (s, d) in cyc_edges else ""
            out.append(
                f'  "{s}" -> "{d}" '
                f'[label="{e.path}:{e.line}", fontsize=8{color}];')
        out.append("}")
        return "\n".join(out)


# ---------------------------------------------------------------------------
# helpers


def _module_of(rel_path: str) -> str:
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _sccs(edges: Set[Tuple[str, str]]) -> List[Set[str]]:
    """Tarjan SCCs over the edge set (iterative)."""
    graph: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for s, d in edges:
        graph.setdefault(s, []).append(d)
        nodes.add(s)
        nodes.add(d)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(graph.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(graph.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                sccs.append(scc)
    return sccs


# ---------------------------------------------------------------------------
# per-file extraction


class _FileModel:
    def __init__(self, ctx):
        self.ctx = ctx
        self.rel_path = ctx.rel_path
        self.module = _module_of(ctx.rel_path)
        self.imports: Dict[str, str] = {}
        self.classes: Set[str] = set()
        self.lock_defs: Dict[str, LockDef] = {}
        self.lock_getters: Dict[str, str] = {}   # in-file qual -> lock id
        self.queue_attrs: Dict[str, Set[str]] = {}  # class -> attrs
        self.attr_assigns: Dict[str, Set[str]] = {}  # class -> all attrs
        self.collective_jit_funcs: Set[str] = set()  # in-file quals
        self.module_has_collectives = any(
            name in ctx.source for name in _COLLECTIVE_NAMES)
        self.funcs: Dict[str, _Func] = {}
        self._collect_imports()
        self._collect_locks_and_classes()
        self._collect_jit_collectives()
        self._collect_queue_attrs()
        self._collect_getters()
        self._scan_functions()

    # -- import table ----------------------------------------------------

    def _collect_imports(self) -> None:
        pkg_parts = self.module.split(".")
        for node in self.ctx.walk(ast.ImportFrom):
            mod = node.module or ""
            if node.level:  # relative import -> absolute
                base = pkg_parts[: len(pkg_parts) - node.level]
                mod = ".".join(base + ([mod] if mod else []))
            for a in node.names:
                self.imports[a.asname or a.name] = (
                    f"{mod}.{a.name}" if mod else a.name)
        for node in self.ctx.walk(ast.Import):
            for a in node.names:
                # `import x.y as z` binds z -> x.y; bare `import x.y`
                # binds only the root name x
                self.imports[a.asname or a.name.split(".", 1)[0]] = \
                    a.name if a.asname else a.name.split(".", 1)[0]

    def _canonical(self, dn: Optional[str]) -> Optional[str]:
        """Rewrite a dotted name's root through the import table
        (``_threading.RLock`` -> ``threading.RLock``)."""
        if not dn:
            return dn
        root, _, rest = dn.partition(".")
        target = self.imports.get(root)
        if target is None:
            return dn
        return f"{target}.{rest}" if rest else target

    def _lock_ctor(self, call: ast.Call) -> Optional[Tuple[str, Optional[ast.AST]]]:
        dn = self._canonical(dotted_name(call.func))
        kind = _LOCK_CTORS.get(dn or "")
        if kind is None:
            return None
        arg = call.args[0] if (kind == "condition" and call.args) else None
        for kw in call.keywords:
            if kw.arg == "lock":
                arg = kw.value
        return kind, arg

    # -- lock + class collection ----------------------------------------

    def _collect_locks_and_classes(self) -> None:
        ctx = self.ctx
        for node in ctx.walk(ast.ClassDef):
            self.classes.add(node.name)
        for node in ctx.walk(ast.Assign):
            if not isinstance(node.value, ast.Call):
                continue
            ctor = self._lock_ctor(node.value)
            if ctor is None:
                continue
            kind, cond_arg = ctor
            for t in node.targets:
                self._define_lock(t, node, kind, cond_arg)

    def _define_lock(self, target: ast.AST, node: ast.Assign, kind: str,
                     cond_arg: Optional[ast.AST]) -> None:
        ctx = self.ctx
        qual = ctx.qualname(node)
        alias = None
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            # self.X = threading.Lock() inside some method of class C
            cls = qual.split(".")[0] if qual != "<module>" else None
            if cls is None or cls not in self.classes:
                return
            lock_id = f"{self.module}.{cls}.{target.attr}"
            if cond_arg is not None:
                adn = dotted_name(cond_arg)
                if adn and adn.startswith("self."):
                    alias = f"{self.module}.{cls}.{adn[5:]}"
        elif isinstance(target, ast.Name):
            if qual == "<module>":
                lock_id = f"{self.module}.{target.id}"
            else:
                lock_id = f"{self.module}.{qual}.{target.id}"
        else:
            return
        self.lock_defs[lock_id] = LockDef(
            id=lock_id, kind=kind, path=self.rel_path,
            line=node.lineno, alias_of=alias)

    def _collect_getters(self) -> None:
        """Module-level functions whose body is (docstring +) ``return
        <module lock>`` — e.g. ``mesh_dispatch_lock()``."""
        for node in self.ctx.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            body = [s for s in node.body
                    if not (isinstance(s, ast.Expr)
                            and isinstance(s.value, ast.Constant))]
            if len(body) != 1 or not isinstance(body[0], ast.Return):
                continue
            ret = body[0].value
            if isinstance(ret, ast.Name):
                lid = f"{self.module}.{ret.id}"
                if lid in self.lock_defs:
                    self.lock_getters[node.name] = lid

    def _collect_jit_collectives(self) -> None:
        from tools.graftlint.rules import _decorator_is_jit
        for node in self.ctx.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if not any(_decorator_is_jit(d) for d in node.decorator_list):
                continue
            names = {n.attr for n in ast.walk(node)
                     if isinstance(n, ast.Attribute)}
            names |= {n.id for n in ast.walk(node)
                      if isinstance(n, ast.Name)}
            # a jitted entry is collective-bearing if its traced body
            # names a collective primitive, or builds a shard_map program
            # in a module that uses collectives (out_specs reassembly is
            # itself a collective even without an explicit all_gather)
            if names & _COLLECTIVE_NAMES or (
                    self.module_has_collectives
                    and names & {"_shard_map", "shard_map"}):
                self.collective_jit_funcs.add(node.name)

    def _collect_queue_attrs(self) -> None:
        for node in self.ctx.walk(ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = self.ctx.qualname(node).split(".")[0]
                    # every instance-attr assignment: self.X() where X is
                    # a stored value (callback, handle) must not resolve
                    # to some unrelated project function by name
                    self.attr_assigns.setdefault(cls, set()).add(t.attr)
                    if isinstance(node.value, ast.Call) and \
                            self._canonical(dotted_name(
                                node.value.func)) in _QUEUE_CTORS:
                        self.queue_attrs.setdefault(
                            cls, set()).add(t.attr)

    # -- function scanning ----------------------------------------------

    def _scan_functions(self) -> None:
        from tools.graftlint.rules import _decorator_is_jit
        ctx = self.ctx
        for node in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            qual = self._func_qual(node)
            cls = self._owner_class(node)
            f = _Func(key=f"{self.module}::{qual}", module=self.module,
                      qual=qual, path=self.rel_path, line=node.lineno,
                      cls=cls,
                      jitted=any(_decorator_is_jit(d)
                                 for d in node.decorator_list))
            self._collect_locals(node, f)
            _Scanner(self, f).scan(node.body, ())
            self.funcs[qual] = f

    def _func_qual(self, node: ast.AST) -> str:
        parts = [node.name]
        for parent, field in self.ctx.ancestry(node):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)) \
                    and field != "decorator_list":
                parts.append(parent.name)
        return ".".join(reversed(parts))

    def _owner_class(self, node: ast.AST) -> Optional[str]:
        parent, field = self.ctx.parent_of(node)
        if isinstance(parent, ast.ClassDef) and field == "body":
            return parent.name
        return None

    def _collect_locals(self, node, f: _Func) -> None:
        """Locks and queues bound to local names inside this function
        (owned by this scope, not a nested def)."""
        ctx = self.ctx
        for n in ast.walk(node):
            if not isinstance(n, ast.Assign) or \
                    not isinstance(n.value, ast.Call):
                continue
            if ctx.enclosing_scope(n) is not node:
                continue
            dn = self._canonical(dotted_name(n.value.func))
            ctor = self._lock_ctor(n.value)
            for t in n.targets:
                if not isinstance(t, ast.Name):
                    continue
                if ctor is not None:
                    lid = f"{self.module}.{f.qual}.{t.id}"
                    self.lock_defs[lid] = LockDef(
                        id=lid, kind=ctor[0], path=self.rel_path,
                        line=n.lineno)
                    f.local_locks[t.id] = lid
                elif dn in _QUEUE_CTORS:
                    f.local_queues.add(t.id)
                elif dn and (dn.endswith("_jit") or dn in
                             ("_shard_map", "shard_map")):
                    f.jit_locals.add(t.id)


class _Scanner:
    """Walks one function body tracking the held-lock set through
    ``with`` nesting, emitting events."""

    def __init__(self, fm: _FileModel, f: _Func):
        self.fm = fm
        self.f = f

    # -- lock expression resolution (symbolic; resolved globally) -------

    def resolve_lock(self, expr: ast.AST) -> Optional[tuple]:
        """A symbolic lock reference for a with-item / acquire receiver,
        or None if it doesn't look like a lock."""
        if isinstance(expr, ast.Call):
            # with mesh_dispatch_lock():  /  with self._lock_for(x): ...
            dn = dotted_name(expr.func)
            if dn is None:
                return None
            return ("getter", dn)
        dn = dotted_name(expr)
        if dn is None:
            return None
        if dn.startswith("self."):
            attr = dn[5:]
            if "." in attr:
                return None
            return ("selfattr", self.f.cls, attr)
        if "." not in dn:
            if dn in self.f.local_locks:
                return ("exact", self.f.local_locks[dn])
            return ("global", dn)
        return ("dotted", dn)

    # -- statement recursion --------------------------------------------

    def scan(self, stmts: Sequence[ast.stmt], held: Tuple[str, ...]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(st, (ast.With, ast.AsyncWith)):
                self._scan_with(st, held)
                continue
            # header expressions run under the current held set
            for expr in self._header_exprs(st):
                self._scan_expr(expr, held)
            for body in self._bodies(st):
                self.scan(body, held)
            if not self._bodies(st) and not self._header_exprs(st):
                self._scan_expr(st, held)

    @staticmethod
    def _bodies(st: ast.stmt) -> List[Sequence[ast.stmt]]:
        out = []
        for field in ("body", "orelse", "finalbody"):
            b = getattr(st, field, None)
            if b:
                out.append(b)
        for h in getattr(st, "handlers", ()) or ():
            out.append(h.body)
        return out

    @staticmethod
    def _header_exprs(st: ast.stmt) -> List[ast.AST]:
        if isinstance(st, (ast.If, ast.While)):
            return [st.test]
        if isinstance(st, (ast.For, ast.AsyncFor)):
            return [st.iter]
        if isinstance(st, ast.Try):
            return []
        if _Scanner._bodies(st):
            return []
        return []

    def _scan_with(self, st, held: Tuple[str, ...]) -> None:
        """Every Name/Attribute/zero-arg-call with-item is a *candidate*
        acquisition; global resolution against the lock model decides
        whether it is one (``with open(...)`` resolves to nothing and
        the event is dropped). Events whose lock does not resolve
        contribute nothing to held-sets or edges."""
        acquired: List[str] = []
        for item in st.items:
            ref = self.resolve_lock(item.context_expr)
            if ref is not None:
                recv = ast.dump(item.context_expr)
                self.f.events.append(_Event(
                    kind="acquire", line=item.context_expr.lineno,
                    held=held + tuple(acquired),
                    lock=None, callee=ref, direct_receiver=recv))
                acquired.append(f"@{len(self.f.events) - 1}")
            if isinstance(item.context_expr, ast.Call):
                # also record call edges for context-manager factories
                # (a non-getter `with self.x.scope():` still calls code)
                self._scan_expr(item.context_expr, held)
        self.scan(st.body, held + tuple(acquired))

    # -- expression handling --------------------------------------------

    def _scan_expr(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        # calls inside nested defs/lambdas run later, not here
        skip: Set[int] = set()
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                for sub in ast.walk(n):
                    skip.add(id(sub))
        for call in ast.walk(node):
            if isinstance(call, ast.Call) and id(call) not in skip:
                self._classify_call(call, held)

    def _classify_call(self, call: ast.Call, held: Tuple[str, ...]) -> None:
        fm, f = self.fm, self.f
        func = call.func
        dn = dotted_name(func)

        # explicit lock.acquire() — a point event (the extent of the
        # critical section is unknowable without pairing releases)
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            ref = self.resolve_lock(func.value)
            if ref is not None:
                f.events.append(_Event(
                    kind="acquire", line=call.lineno, held=held,
                    callee=ref,
                    direct_receiver=ast.dump(func.value)))
                return

        # blocking primitives -------------------------------------------
        if dn is not None and fm._canonical(dn) in ("time.sleep",):
            f.events.append(_Event(kind="blocking", line=call.lineno,
                                   held=held, detail="time.sleep",
                                   category="sleep"))
            return
        if isinstance(func, ast.Attribute):
            attr = func.attr
            recv = func.value
            if attr == "result":
                f.events.append(_Event(
                    kind="blocking", line=call.lineno, held=held,
                    category="future-result",
                    detail=f"{dotted_name(recv) or '<expr>'}.result()"))
                return
            if attr == "get" and self._is_queue(recv):
                f.events.append(_Event(
                    kind="blocking", line=call.lineno, held=held,
                    category="queue-get",
                    detail=f"{dotted_name(recv) or '<expr>'}.get()"))
                return
            if attr == "wait":
                # callee carries the receiver's lock ref: a cv.wait()
                # releases its own lock, which resolution subtracts
                # from the effective held-set
                f.events.append(_Event(
                    kind="blocking", line=call.lineno, held=held,
                    callee=self.resolve_lock(recv), category="wait",
                    detail=f"{dotted_name(recv) or '<expr>'}.wait()"))
                return
            if attr in _RPC_NAMES:
                f.events.append(_Event(
                    kind="blocking", line=call.lineno, held=held,
                    category="rpc",
                    detail=f"{dotted_name(recv) or '<expr>'}.{attr}()"))
                # fall through: also record the call edge (e.g. self._call
                # resolves to a project method whose summary matters)

        # direct device dispatch (old rule covers depth 0; we only record
        # the fact for interprocedural propagation)
        if is_dispatch_call(call, fm.ctx):
            if f.direct_dispatch is None:
                f.direct_dispatch = call.lineno
            return

        # collective dispatch, pattern: invoking a local name bound from
        # a *_jit(...) / _shard_map(...) factory in a collective module
        if isinstance(func, ast.Name) and func.id in f.jit_locals \
                and fm.module_has_collectives:
            f.events.append(_Event(kind="collective", line=call.lineno,
                                   held=held, detail=f"{func.id}(...)"))
            return

        # plain call edge ------------------------------------------------
        desc = self._call_descriptor(call)
        if desc is not None:
            f.events.append(_Event(kind="call", line=call.lineno,
                                   held=held, callee=desc,
                                   detail=dn or desc[-1]))

    def _call_descriptor(self, call: ast.Call) -> Optional[tuple]:
        func = call.func
        if isinstance(func, ast.Name):
            return ("name", func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self":
                return ("self", func.attr)
            dn = dotted_name(func)
            if dn is not None:
                return ("dotted", dn)
            return ("attr", func.attr)
        return None

    def _is_queue(self, recv: ast.AST) -> bool:
        if isinstance(recv, ast.Name):
            return recv.id in self.f.local_queues
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and self.f.cls:
            return recv.attr in self.fm.queue_attrs.get(self.f.cls, set())
        return False


# ---------------------------------------------------------------------------
# global analysis


class Analyzer:
    def __init__(self, contexts: Dict[str, "FileContext"]):
        self.files = {rel: _FileModel(ctx)
                      for rel, ctx in sorted(contexts.items())}
        self.locks: Dict[str, LockDef] = {}
        self.getters: Dict[str, str] = {}     # "module::qual" -> lock id
        self.funcs: Dict[str, _Func] = {}
        self.by_name: Dict[str, List[str]] = {}
        self.by_method: Dict[Tuple[str, str], List[str]] = {}
        self.collective_funcs: Set[str] = set()  # keys of jit+collective
        for fm in self.files.values():
            self.locks.update(fm.lock_defs)
            for qual, lid in fm.lock_getters.items():
                self.getters[f"{fm.module}::{qual}"] = lid
            for qual, f in fm.funcs.items():
                self.funcs[f.key] = f
                simple = qual.rsplit(".", 1)[-1]
                self.by_name.setdefault(simple, []).append(f.key)
                if f.cls:
                    self.by_method.setdefault(
                        (f.cls, simple), []).append(f.key)
            for qual in fm.collective_jit_funcs:
                self.collective_funcs.add(f"{fm.module}::{qual}")
        # lock attr name -> ids (for cross-class fallback)
        self.lock_attr_index: Dict[str, List[str]] = {}
        for lid in self.locks:
            self.lock_attr_index.setdefault(
                lid.rsplit(".", 1)[-1], []).append(lid)
        self.project_modules: Set[str] = {
            fm.module for fm in self.files.values()}

    def _is_project_module(self, mod: str) -> bool:
        """Whether a dotted import target points into the analyzed tree
        (``os``/``subprocess``/... must NOT fall back to by-name
        matching — ``os.replace`` is not the Collection.replace API)."""
        return any(m == mod or m.startswith(mod + ".")
                   or mod.startswith(m + ".")
                   for m in self.project_modules)

    # -- resolution ------------------------------------------------------

    def _follow_alias(self, lid: Optional[str]) -> Optional[str]:
        seen = set()
        while lid is not None and lid in self.locks \
                and self.locks[lid].alias_of and lid not in seen:
            seen.add(lid)
            lid = self.locks[lid].alias_of
        return lid

    def resolve_lock_ref(self, fm: _FileModel, f: _Func,
                         ref: tuple) -> Optional[str]:
        kind = ref[0]
        if kind == "exact":
            return self._follow_alias(ref[1])
        if kind == "selfattr":
            cls, attr = ref[1], ref[2]
            if cls:
                lid = f"{fm.module}.{cls}.{attr}"
                if lid in self.locks:
                    return self._follow_alias(lid)
            cands = self.lock_attr_index.get(attr, [])
            if len(cands) == 1:
                return self._follow_alias(cands[0])
            return None
        if kind == "global":
            lid = f"{fm.module}.{ref[1]}"
            if lid in self.locks:
                return self._follow_alias(lid)
            tgt = fm.imports.get(ref[1])
            if tgt and tgt in self.locks:
                return self._follow_alias(tgt)
            return None
        if kind == "dotted":
            dn = fm._canonical(ref[1])
            if dn and dn in self.locks:
                return self._follow_alias(dn)
            # obj._lock style: attr-name fallback when globally unique
            attr = ref[1].rsplit(".", 1)[-1]
            cands = self.lock_attr_index.get(attr, [])
            if len(cands) == 1:
                return self._follow_alias(cands[0])
            return None
        if kind == "getter":
            keys = self.resolve_call(fm, None, ("name", ref[1])) \
                if "." not in ref[1] else \
                self.resolve_call(fm, None, ("dotted", ref[1]))
            for k in keys:
                if k in self.getters:
                    return self._follow_alias(self.getters[k])
            return None
        return None

    def resolve_call(self, fm: _FileModel, f: Optional[_Func],
                     desc: tuple) -> List[str]:
        kind = desc[0]
        if kind == "name":
            name = desc[1]
            if f is not None:
                # nested def in the same function
                nested = f"{f.qual}.{name}"
                if nested in fm.funcs:
                    return [fm.funcs[nested].key]
            if name in fm.funcs:
                return [fm.funcs[name].key]
            if name in fm.classes:
                init = f"{name}.__init__"
                if init in fm.funcs:
                    return [fm.funcs[init].key]
                return []
            tgt = fm.imports.get(name)
            if tgt and "." in tgt:
                mod, _, fname = tgt.rpartition(".")
                key = f"{mod}::{fname}"
                if key in self.funcs:
                    return [key]
                if key in self.getters:
                    return [key]
                # imported class
                ikey = f"{mod}::{fname}.__init__"
                if ikey in self.funcs:
                    return [ikey]
            return []
        if kind == "self":
            name = desc[1]
            if f is not None and f.cls:
                mkey = f"{fm.module}::{f.cls}.{name}"
                if mkey in self.funcs:
                    return [mkey]
                cands = self.by_method.get((f.cls, name))
                if cands:
                    return list(cands)
                if name in fm.attr_assigns.get(f.cls, set()):
                    return []  # stored callback/handle, target unknowable
            return self._by_name(name)
        if kind == "dotted":
            dn = desc[1]
            root, _, rest = dn.partition(".")
            tgt = fm.imports.get(root)
            if tgt and rest:
                # module alias: sharded_search.sharded_flat_search(...)
                mod_attr = f"{tgt}.{rest}"
                mod, _, fname = mod_attr.rpartition(".")
                key = f"{mod}::{fname}"
                if key in self.funcs:
                    return [key]
                if key in self.getters:
                    return [key]
                if not self._is_project_module(tgt):
                    return []  # stdlib/3rd-party call, never by-name
            return self._by_name(dn.rsplit(".", 1)[-1])
        if kind == "attr":
            return self._by_name(desc[1])
        return []

    def _by_name(self, name: str) -> List[str]:
        if name in _NO_BYNAME or name.startswith("__"):
            return []
        cands = self.by_name.get(name, [])
        if 0 < len(cands) <= _BYNAME_CAP:
            return list(cands)
        return []

    # -- propagation -----------------------------------------------------

    def run(self) -> ConcurrencyModel:
        model = ConcurrencyModel()
        model.locks = dict(self.locks)

        # resolve every event's symbolic pieces once
        resolved: Dict[str, List[dict]] = {}
        for fm in self.files.values():
            for f in fm.funcs.values():
                evs = []
                for ev in f.events:
                    e = {"ev": ev, "lock": None, "callees": []}
                    if ev.kind == "acquire":
                        e["lock"] = self.resolve_lock_ref(fm, f, ev.callee)
                    elif ev.kind == "call":
                        e["callees"] = self.resolve_call(fm, f, ev.callee)
                    elif ev.kind == "blocking" and ev.callee is not None:
                        e["lock"] = self.resolve_lock_ref(fm, f, ev.callee)
                    evs.append(e)
                resolved[f.key] = evs

        held_ids = self._materialize_held(resolved)

        # transitive acquire sets --------------------------------------
        acq: Dict[str, Set[str]] = {k: set() for k in self.funcs}
        calls: Dict[str, Set[str]] = {k: set() for k in self.funcs}
        for key, evs in resolved.items():
            for e in evs:
                if e["ev"].kind == "acquire" and e["lock"]:
                    acq[key].add(e["lock"])
                for c in e["callees"]:
                    if c in self.funcs:
                        calls[key].add(c)
        acq_star = {k: set(v) for k, v in acq.items()}
        changed = True
        while changed:
            changed = False
            for k in self.funcs:
                for c in calls[k]:
                    before = len(acq_star[k])
                    acq_star[k] |= acq_star[c]
                    if len(acq_star[k]) != before:
                        changed = True

        # transitive blocking summaries --------------------------------
        # kind -> representative chain [(path, line, what)]
        blk: Dict[str, Dict[str, list]] = {k: {} for k in self.funcs}
        for key, evs in resolved.items():
            f = self.funcs[key]
            for e in evs:
                ev = e["ev"]
                if ev.kind == "blocking":
                    blk[key].setdefault(
                        ev.category or "blocking",
                        [(f.path, ev.line, ev.detail)])
            if f.direct_dispatch is not None:
                blk[key].setdefault(
                    "device-dispatch",
                    [(f.path, f.direct_dispatch, "device dispatch")])
        changed = True
        while changed:
            changed = False
            for k in self.funcs:
                f = self.funcs[k]
                for e in resolved[k]:
                    ev = e["ev"]
                    if ev.kind != "call":
                        continue
                    for c in e["callees"]:
                        if c not in blk:
                            continue
                        for bkind, chain in blk[c].items():
                            if bkind in blk[k] or len(chain) >= _CHAIN_MAX:
                                continue
                            blk[k][bkind] = \
                                [(f.path, ev.line, ev.detail)] + chain
                            changed = True

        self._edges(model, resolved, held_ids, acq_star)
        self._cycle_findings(model)
        self._blocking_findings(model, resolved, held_ids, blk)
        self._collective_findings(model, resolved, held_ids)
        model.violations.sort(
            key=lambda v: (v.path, v.line, v.col, v.rule, v.message))
        return model

    def _materialize_held(self, resolved) -> Dict[str, List[Tuple[str, ...]]]:
        """Per function, per event: the held set as resolved lock ids.
        With-acquired locks are referenced as '@<event index>' in
        ``held`` — map those through each event's resolved lock."""
        out: Dict[str, List[Tuple[str, ...]]] = {}
        for key, evs in resolved.items():
            per_ev: List[Tuple[str, ...]] = []
            for e in evs:
                ids = []
                for h in e["ev"].held:
                    if h.startswith("@"):
                        lid = evs[int(h[1:])]["lock"]
                    else:
                        lid = h
                    if lid:
                        ids.append(lid)
                per_ev.append(tuple(dict.fromkeys(ids)))
            out[key] = per_ev
        return out

    def _add_edge(self, model, src, dst, f: _Func, line: int,
                  via: str = "") -> None:
        if (src, dst) in model.edges:
            return
        model.edges[(src, dst)] = Edge(
            src=src, dst=dst, path=f.path, line=line, func=f.qual, via=via)

    def _edges(self, model, resolved, held_ids, acq_star) -> None:
        for key, evs in resolved.items():
            f = self.funcs[key]
            for i, e in enumerate(evs):
                ev = e["ev"]
                held = held_ids[key][i]
                if ev.kind == "acquire" and e["lock"]:
                    dst = e["lock"]
                    for src in held:
                        if src == dst:
                            self._self_edge(model, src, f, ev, direct=True)
                        else:
                            self._add_edge(model, src, dst, f, ev.line)
                elif ev.kind == "call" and held:
                    for c in e["callees"]:
                        for dst in acq_star.get(c, ()):
                            for src in held:
                                if src == dst:
                                    self._self_edge(model, src, f, ev,
                                                    direct=False)
                                else:
                                    self._add_edge(
                                        model, src, dst, f, ev.line,
                                        via=f"via {ev.detail}()")

    def _self_edge(self, model, lid, f: _Func, ev: _Event,
                   direct: bool) -> None:
        ld = self.locks.get(lid)
        if ld is None or ld.reentrant:
            return
        # class-attr locks exist once per instance: a call-propagated
        # re-entry may hit a *different* instance, which is ordering-
        # ambiguous, not a certain deadlock — only direct syntactic
        # re-acquisition, or any re-entry of a true module-global
        # singleton, is reported.
        is_global = "." not in lid[len(_module_of(ld.path)) + 1:]
        if direct or is_global:
            self._add_edge(model, lid, lid, f, ev.line,
                           via="" if direct else f"via {ev.detail}()")

    # -- findings --------------------------------------------------------

    def _mk(self, rule, sev, f_path, line, symbol, message) -> Violation:
        fm = self.files.get(f_path)
        snippet = fm.ctx.line_snippet(line) if fm else ""
        return Violation(rule=rule, path=f_path, line=line, col=0,
                         severity=sev, message=message, symbol=symbol,
                         snippet=snippet)

    def _cycle_findings(self, model) -> None:
        edge_pairs = set(model.edges)
        for scc in _sccs(edge_pairs):
            members = sorted(scc)
            cyc = [(s, d) for (s, d) in sorted(edge_pairs)
                   if s in scc and d in scc]
            if len(scc) == 1:
                lid = members[0]
                if (lid, lid) not in edge_pairs:
                    continue
                cyc = [(lid, lid)]
            if not cyc:
                continue
            sites = []
            for (s, d) in cyc:
                e = model.edges[(s, d)]
                note = f" {e.via}" if e.via else ""
                sites.append(f"{s} -> {d} at {e.path}:{e.line} "
                             f"({e.func}){note}")
            anchor = model.edges[cyc[0]]
            if len(scc) == 1:
                msg = (f"non-reentrant lock {members[0]} can be "
                       "re-acquired while already held (self-deadlock): "
                       + "; ".join(sites))
            else:
                msg = ("lock-order cycle (potential deadlock) between "
                       + ", ".join(members) + ": " + "; ".join(sites)
                       + " — pick one order and enforce it, or alias "
                         "the locks")
            v = self._mk(LOCK_ORDER_CYCLE, SEV_ERROR, anchor.path,
                         anchor.line, anchor.func, msg)
            model.violations.append(v)

    def _blocking_findings(self, model, resolved, held_ids, blk) -> None:
        seen: Set[Tuple[str, int, str]] = set()
        for key, evs in resolved.items():
            f = self.funcs[key]
            for i, e in enumerate(evs):
                ev = e["ev"]
                held = held_ids[key][i]
                if not held:
                    continue
                if ev.kind == "blocking":
                    eff = tuple(h for h in held if h != e["lock"])
                    if not eff:
                        continue  # cv.wait() under only its own lock
                    k = (f.path, ev.line)
                    if k in seen:
                        continue
                    seen.add(k)
                    model.violations.append(self._mk(
                        BLOCKING_UNDER_LOCK, SEV_WARNING, f.path, ev.line,
                        f.qual,
                        f"{ev.detail} blocks while holding "
                        f"{', '.join(eff)} — every thread contending for "
                        "the lock stalls behind this wait; move it "
                        "outside the critical section or bound it"))
                elif ev.kind == "call":
                    for c in e["callees"]:
                        chains = blk.get(c, {})
                        for bkind, chain in sorted(chains.items()):
                            eff = held
                            if bkind == "device-dispatch":
                                # serializing device enqueues IS the mesh
                                # dispatch lock's job
                                eff = tuple(h for h in held
                                            if h != MESH_LOCK_ID)
                            if not eff:
                                continue
                            k = (f.path, ev.line)
                            if k in seen:
                                continue
                            seen.add(k)
                            hops = " -> ".join(
                                f"{p}:{ln} {what}"
                                for (p, ln, what) in chain)
                            model.violations.append(self._mk(
                                BLOCKING_UNDER_LOCK, SEV_WARNING,
                                f.path, ev.line, f.qual,
                                f"call to {ev.detail}() while holding "
                                f"{', '.join(eff)} reaches a blocking "
                                f"{bkind} ({hops}) — snapshot under the "
                                "lock, release, then block"))

    def _collective_findings(self, model, resolved, held_ids) -> None:
        # which functions can be entered without the mesh lock held:
        # roots (no known callers) start unlocked; an edge whose call
        # site holds the lock does not propagate unlocked-ness
        incoming: Dict[str, List[Tuple[str, bool]]] = \
            {k: [] for k in self.funcs}
        for key, evs in resolved.items():
            for i, e in enumerate(evs):
                if e["ev"].kind != "call":
                    continue
                locked = MESH_LOCK_ID in held_ids[key][i]
                for c in e["callees"]:
                    if c in incoming:
                        incoming[c].append((key, locked))
        unlocked: Dict[str, bool] = {
            k: not incoming[k] for k in self.funcs}
        changed = True
        while changed:
            changed = False
            for k, edges_in in incoming.items():
                if unlocked[k]:
                    continue
                for (caller, locked) in edges_in:
                    if not locked and unlocked.get(caller, True):
                        unlocked[k] = True
                        changed = True
                        break

        for key, evs in resolved.items():
            f = self.funcs[key]
            if f.jitted:
                # a jitted body executes at trace time; the runtime
                # enqueue order is governed by whoever dispatches it
                continue
            for i, e in enumerate(evs):
                ev = e["ev"]
                coll = ev.kind == "collective" or (
                    ev.kind == "call"
                    and any(c in self.collective_funcs
                            for c in e["callees"]))
                if not coll:
                    continue
                held = held_ids[key][i]
                if MESH_LOCK_ID in held:
                    continue
                if not unlocked.get(key, True):
                    continue  # every caller path already holds the lock
                model.violations.append(self._mk(
                    UNLOCKED_COLLECTIVE, SEV_ERROR, f.path, ev.line,
                    f.qual,
                    f"collective-bearing mesh program {ev.detail} "
                    "dispatched without mesh_dispatch_lock held — two "
                    "concurrent collective programs can interleave "
                    "per-device enqueues and deadlock at the rendezvous "
                    "(the PR 7 bug); wrap the dispatch in `with "
                    "mesh_dispatch_lock():` (see docs/mesh.md)"))


# ---------------------------------------------------------------------------
# entry points + cache


def analyze_contexts(contexts: Dict[str, "FileContext"]) -> ConcurrencyModel:
    """Run the whole-program analysis over pre-built FileContexts."""
    return Analyzer(contexts).run()


def analyze_sources(sources: Dict[str, str]) -> ConcurrencyModel:
    """Test/utility entry: analyze raw sources keyed by rel path."""
    from tools.graftlint.engine import FileContext
    return analyze_contexts(
        {rel: FileContext(src, rel) for rel, src in sources.items()})


def check_contexts(contexts: Dict[str, "FileContext"],
                   meta: Optional[Dict[str, Tuple[int, int]]] = None,
                   cache_path: Optional[Path] = None) -> ConcurrencyModel:
    """Analysis with the shared mtime cache (``passcache``): ``meta``
    maps rel path -> (mtime_ns, size). A warm cache (identical version
    + file set + stamps) replays the stored findings and edges without
    re-running the pass; anything else recomputes and rewrites."""
    import time as _time

    from tools.graftlint import passcache

    t0 = _time.perf_counter()
    data = passcache.load(cache_path, CONCURRENCY_VERSION, meta)
    if data is not None:
        try:
            model = ConcurrencyModel()
            model.cache_state = "warm"
            for d in data["violations"]:
                model.violations.append(Violation(**d))
            for d in data["edges"]:
                e = Edge(**d)
                model.edges[(e.src, e.dst)] = e
            for d in data["locks"]:
                ld = LockDef(**d)
                model.locks[ld.id] = ld
            model.wall_s = _time.perf_counter() - t0
            return model
        except (ValueError, KeyError, TypeError):
            pass  # malformed payload: recompute and overwrite
    model = analyze_contexts(contexts)
    model.cache_state = "cold" if cache_path is not None else "off"
    model.wall_s = _time.perf_counter() - t0
    passcache.store(cache_path, CONCURRENCY_VERSION, meta, {
        "violations": [v.to_dict() for v in model.violations],
        "edges": [dataclasses.asdict(e)
                  for _, e in sorted(model.edges.items())],
        "locks": [dataclasses.asdict(ld)
                  for _, ld in sorted(model.locks.items())],
    })
    return model
