"""Rule registry + the built-in rules.

Every rule is a subclass of :class:`Rule` registered in ``ALL_RULES``.
A rule receives a fully annotated :class:`~tools.graftlint.engine.FileContext`
(parent links, qualnames, import table) and yields :class:`Violation`s.

Adding a rule: subclass ``Rule``, set ``id``/``description``/``rationale``,
implement ``check``, and append an instance to ``ALL_RULES``. Document it
in ``docs/lint.md``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional, Sequence

# Directories (relative, posix) whose files form the latency-critical
# serving path: a host sync here stalls the whole TPU pipeline.
HOT_PATH_DIRS = (
    "weaviate_tpu/ops/",
    "weaviate_tpu/index/",
    "weaviate_tpu/parallel/",
    "weaviate_tpu/query/",
)

# Kernel files: dtype discipline is load-bearing (bf16 MXU inputs, fp32
# accumulators); an implicit float32/float64 literal silently widens math.
KERNEL_DIRS = ("weaviate_tpu/ops/",)

# Packages where a swallowed exception means quiet data loss rather than
# a degraded response.
CRITICAL_EXCEPTION_DIRS = ("weaviate_tpu/cluster/", "weaviate_tpu/backup/")

SEV_WARNING = "warning"
SEV_ERROR = "error"
SEV_CRITICAL = "critical"

_SEV_ORDER = {SEV_WARNING: 0, SEV_ERROR: 1, SEV_CRITICAL: 2}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # posix path relative to the lint root
    line: int
    col: int
    severity: str
    message: str
    symbol: str  # enclosing qualname, or "<module>"
    snippet: str  # stripped offending source line, truncated

    def fingerprint(self) -> tuple:
        """Identity used for baseline matching — deliberately excludes
        line/col so unrelated edits above a grandfathered violation do
        not churn the baseline."""
        return (self.rule, self.path, self.symbol, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    id: str = ""
    description: str = ""
    rationale: str = ""

    def check(self, ctx) -> Iterator[Violation]:  # pragma: no cover
        raise NotImplementedError

    # -- shared helpers -------------------------------------------------

    def violation(self, ctx, node: ast.AST, message: str,
                  severity: str = SEV_ERROR) -> Violation:
        return Violation(
            rule=self.id,
            path=ctx.rel_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            severity=severity,
            message=message,
            symbol=ctx.qualname(node),
            snippet=ctx.snippet(node),
        )


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _path_in(rel_path: str, dirs: Sequence[str]) -> bool:
    return any(rel_path.startswith(d) for d in dirs)


def _contains_root_name(node: ast.AST, names: Sequence[str]) -> bool:
    """Whether any Name in the subtree matches ``names`` (e.g. jnp/jax)."""
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node)
    )


# jax API calls that return host metadata (device handles, counts), not
# device arrays: neither a taint source nor device dispatch.
NON_DISPATCH_JAX = frozenset({
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_count", "jax.process_index",
    "jax.default_backend", "jax.named_scope",
})

_DISPATCH_ROOTS = ("jax", "jnp", "pl")


def is_dispatch_call(call: ast.Call, ctx) -> bool:
    """Whether a call dispatches device work: jax/jnp/pl-rooted (minus
    the NON_DISPATCH_JAX metadata calls) or resolved through the file's
    weaviate_tpu.ops imports/aliases. ONE matcher shared by the
    lock-across-device-call and host-loop-over-mesh rules, so the two
    can never drift apart on what counts as a dispatch."""
    dn = dotted_name(call.func)
    if not dn or dn in NON_DISPATCH_JAX:
        return False
    root = dn.split(".", 1)[0]
    return (root in _DISPATCH_ROOTS or root in ctx.ops_aliases
            or (root in ctx.ops_imports and "." not in dn))


# ---------------------------------------------------------------------------
# 1. host-sync-in-hot-path


class HostSyncInHotPath(Rule):
    id = "host-sync-in-hot-path"
    description = (
        "device->host transfer (np.asarray/.item()/.tolist()/"
        "block_until_ready/float(jnp...)) of a device value inside the "
        "serving hot path"
    )
    rationale = (
        "Each transfer blocks the Python thread on the device stream and "
        "flushes the async dispatch pipeline; one stray .item() turns a "
        "fully-overlapped TPU search into lockstep round trips. The rule "
        "runs a per-scope taint pass so host-side input prep "
        "(np.asarray(user_queries)) is NOT flagged — only values that "
        "provably come from a jax/ops/parallel call are."
    )

    _NP_FUNCS = frozenset({
        "np.asarray", "np.array", "numpy.asarray", "numpy.array",
        "np.copy", "numpy.copy", "np.ascontiguousarray",
    })
    _SYNC_METHODS = frozenset({"item", "tolist"})
    _SCALAR_CASTS = frozenset({"float", "int", "bool"})
    _DEVICE_ROOTS = ("jnp", "jax", "pl")

    def _is_device_call(self, call: ast.Call, ctx) -> bool:
        dn = dotted_name(call.func)
        if not dn or dn in NON_DISPATCH_JAX:
            return False
        root = dn.split(".", 1)[0]
        if root in self._DEVICE_ROOTS:
            # jnp.asarray / jax.device_put etc. *produce* device values
            return True
        if root in ctx.device_aliases:
            return True
        if "." not in dn and dn in ctx.device_imports:
            return True
        return False

    def _contains_device_value(self, node: ast.AST, tainted, ctx) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and self._is_device_call(n, ctx):
                return True
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in tainted):
                return True
        return False

    def _scope_taint(self, scope, ctx) -> set:
        """Fixpoint over assignments in one scope: a name is tainted if
        it is ever assigned a value derived from a device call or from
        another tainted name. Deliberately flow-insensitive (over-taints
        names reused for host values) — suppress with a reason if hit."""
        tainted: set = set()
        assigns = []
        for n in ast.walk(scope):
            if ctx.enclosing_scope(n) is not scope:
                continue  # owned by a nested function
            if isinstance(n, ast.Assign):
                assigns.append((n.targets, n.value))
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)) \
                    and n.value is not None:
                assigns.append(([n.target], n.value))
        for _ in range(4):  # taint chains deeper than 4 hops don't occur
            changed = False
            for targets, value in assigns:
                if not self._contains_device_value(value, tainted, ctx):
                    continue
                for t in targets:
                    elts = t.elts if isinstance(t, ast.Tuple) else [t]
                    for e in elts:
                        if isinstance(e, ast.Name) and e.id not in tainted:
                            tainted.add(e.id)
                            changed = True
            if not changed:
                break
        return tainted

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, HOT_PATH_DIRS):
            return
        scopes = [ctx.tree] + list(
            ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef))
        for scope in scopes:
            tainted = self._scope_taint(scope, ctx)
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                if ctx.enclosing_scope(node) is not scope:
                    continue
                yield from self._check_call(node, tainted, ctx)

    def _check_call(self, node: ast.Call, tainted, ctx):
        func = node.func
        dn = dotted_name(func)
        if dn in self._NP_FUNCS:
            if node.args and self._contains_device_value(
                    node.args[0], tainted, ctx):
                yield self.violation(
                    ctx, node,
                    f"{dn}(...) on a device value forces a blocking "
                    "device->host copy; keep the hot path on device (jnp) "
                    "or annotate a true host boundary",
                )
        elif isinstance(func, ast.Attribute) \
                and func.attr in self._SYNC_METHODS:
            if self._contains_device_value(func.value, tainted, ctx):
                yield self.violation(
                    ctx, node,
                    f".{func.attr}() on a device value synchronizes with "
                    "the device stream; batch the readback or move it past "
                    "the top-k merge",
                )
        elif isinstance(func, ast.Attribute) \
                and func.attr == "block_until_ready":
            yield self.violation(
                ctx, node,
                ".block_until_ready() is a full pipeline flush — benchmark "
                "harnesses only, never the serving path",
            )
        elif (isinstance(func, ast.Name)
                and func.id in self._SCALAR_CASTS
                and node.args
                and self._contains_device_value(node.args[0], tainted, ctx)):
            yield self.violation(
                ctx, node,
                f"{func.id}() on a device value is an implicit .item() — "
                "a blocking scalar readback",
            )


# ---------------------------------------------------------------------------
# 2. jit-in-loop


def _is_jit_like(call: ast.Call) -> Optional[str]:
    dn = dotted_name(call.func)
    if dn in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return "jax.jit"
    if dn in ("pl.pallas_call", "pallas_call",
              "jax.experimental.pallas.pallas_call"):
        return "pallas_call"
    return None


def _decorator_is_jit(dec: ast.AST) -> bool:
    dn = dotted_name(dec)
    if dn in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return True
    if isinstance(dec, ast.Call):
        inner = dotted_name(dec.func)
        if inner in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return True
        # functools.partial(jax.jit, ...)
        if inner in ("functools.partial", "partial") and dec.args:
            return dotted_name(dec.args[0]) in (
                "jax.jit", "jit", "pjit", "jax.pjit")
    return False


def _decorator_is_cache(dec: ast.AST) -> bool:
    dn = dotted_name(dec)
    if isinstance(dec, ast.Call):
        dn = dotted_name(dec.func)
    return dn in ("functools.lru_cache", "lru_cache",
                  "functools.cache", "cache")


class JitInLoop(Rule):
    id = "jit-in-loop"
    description = (
        "jax.jit / pallas_call constructed inside a loop or per-call "
        "function body (cache-miss => recompile on every invocation)"
    )
    rationale = (
        "jax caches compiled programs by wrapper identity; a wrapper built "
        "inside a request handler or loop is new every time, so XLA "
        "recompiles (100ms-10s) per call instead of once per process."
    )

    def check(self, ctx) -> Iterator[Violation]:
        for node in ctx.walk(ast.Call):
            kind = _is_jit_like(node)
            if kind is None:
                continue
            if ctx.in_decorator(node):
                continue  # @jax.jit / @functools.partial(jax.jit, ...)
            parent, field = ctx.parent_of(node)
            immediately_invoked = (
                isinstance(parent, ast.Call) and field == "func")
            func_chain = ctx.enclosing_functions(node)
            if any(any(_decorator_is_jit(d) for d in f.decorator_list)
                   for f in func_chain):
                continue  # trace-time construction inside an outer jit
            if any(any(_decorator_is_cache(d) for d in f.decorator_list)
                   for f in func_chain):
                continue  # memoized factory
            if ctx.in_loop(node):
                yield self.violation(
                    ctx, node,
                    f"{kind} constructed inside a loop — hoist it to module "
                    "scope or a per-shape cache",
                )
                continue
            if not func_chain:
                continue  # module scope: compiled once per import
            if immediately_invoked:
                yield self.violation(
                    ctx, node,
                    f"immediately-invoked {kind}(f)(...) recompiles on every "
                    "call — bind the jitted wrapper once",
                )
            else:
                yield self.violation(
                    ctx, node,
                    f"{kind} constructed inside "
                    f"{ctx.qualname(node)}() — every call builds a fresh "
                    "wrapper and misses the compile cache; hoist or memoize",
                    severity=SEV_WARNING,
                )


# ---------------------------------------------------------------------------
# 3. nonhashable-static-arg


class NonhashableStaticArg(Rule):
    id = "nonhashable-static-arg"
    description = (
        "list/dict/set literal passed via static_argnums/static_argnames "
        "plumbing (static operands must be hashable)"
    )
    rationale = (
        "Static arguments key the jit compile cache by hash; an unhashable "
        "value raises at call time, and a mutable-but-hashed wrapper "
        "silently defeats cache hits. Tuples only."
    )

    _KEYWORDS = ("static_argnums", "static_argnames")
    _BAD = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp, ast.GeneratorExp)

    def check(self, ctx) -> Iterator[Violation]:
        for node in ctx.walk(ast.Call):
            for kw in node.keywords:
                if kw.arg in self._KEYWORDS and isinstance(kw.value, self._BAD):
                    yield self.violation(
                        ctx, kw.value,
                        f"{kw.arg} given a {type(kw.value).__name__} "
                        "literal — use a tuple so the value is hashable and "
                        "the compile-cache key is stable",
                    )


# ---------------------------------------------------------------------------
# 4. swallowed-exception


class SwallowedException(Rule):
    id = "swallowed-exception"
    description = (
        "bare/blind `except` that neither re-raises nor logs — the classic "
        "quiet-data-loss bug in replication/backup paths"
    )
    rationale = (
        "Weaviate's raft and backup code treats every error as a first-class "
        "result; a blind `except Exception: pass` here converts a failed "
        "replica write into silent divergence that no test observes."
    )

    _BLIND = frozenset({"Exception", "BaseException"})
    _LOG_ATTRS = frozenset({
        "exception", "warning", "warn", "error", "critical", "info",
        "debug", "log", "print_exc",
    })

    def _is_blind(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if dotted_name(t) in self._BLIND:
            return True
        if isinstance(t, ast.Tuple):
            return any(dotted_name(e) in self._BLIND for e in t.elts)
        return False

    def _is_handled(self, handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) and f.attr in self._LOG_ATTRS:
                    return True
                dn = dotted_name(f)
                if dn in ("warnings.warn", "traceback.print_exc"):
                    return True
            # `except Exception as e:` where e is actually consumed
            # (stored on a status object, set on a future, stringified
            # into a reply) is error *handling*, not swallowing.
            if (handler.name and isinstance(n, ast.Name)
                    and n.id == handler.name
                    and isinstance(n.ctx, ast.Load)):
                return True
        return False

    def check(self, ctx) -> Iterator[Violation]:
        for handler in ctx.walk(ast.ExceptHandler):
            if not self._is_blind(handler) or self._is_handled(handler):
                continue
            critical = _path_in(ctx.rel_path, CRITICAL_EXCEPTION_DIRS)
            what = ("bare except" if handler.type is None
                    else "blind except Exception")
            yield self.violation(
                ctx, handler,
                f"{what} with no re-raise and no logging — narrow the type "
                "or log via logging.getLogger('weaviate_tpu.<area>') before "
                "continuing",
                severity=SEV_CRITICAL if critical else SEV_ERROR,
            )


# ---------------------------------------------------------------------------
# 4b. transport-error-swallowed


class TransportErrorSwallowed(Rule):
    id = "transport-error-swallowed"
    description = (
        "`except TransportError: pass` in cluster/ — a replica RPC "
        "failure absorbed with no log, no metric, and no result"
    )
    rationale = (
        "The replication data plane is allowed to tolerate a failed "
        "replica, but never invisibly: an unobserved TransportError is "
        "exactly how a chaos-injected fault (or a real partition) turns "
        "into silent divergence no dashboard shows. Failing the call, "
        "counting it (RPC_FAILURES and friends), logging it, or turning "
        "it into a result (return/continue/raise) all count as handling; "
        "a body that does none of those is flagged."
    )

    _DIRS = ("weaviate_tpu/cluster/",)
    # names the cluster package binds transport failure to
    _TYPES = frozenset({"TransportError", "_REPLICA_ERRORS"})
    _LOG_ATTRS = SwallowedException._LOG_ATTRS
    _METRIC_ATTRS = frozenset({"inc", "dec", "observe", "set"})

    def _names_transport_error(self, t: Optional[ast.AST]) -> bool:
        if t is None:
            return False
        if isinstance(t, ast.Tuple):
            return any(self._names_transport_error(e) for e in t.elts)
        dn = dotted_name(t)
        return bool(dn) and dn.rsplit(".", 1)[-1] in self._TYPES

    def _is_observed(self, handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            # failure becomes a first-class result the caller sees
            if isinstance(n, (ast.Raise, ast.Return, ast.Continue,
                              ast.Break)):
                return True
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) and f.attr in (
                        self._LOG_ATTRS | self._METRIC_ATTRS):
                    return True
                if dotted_name(f) in ("warnings.warn",
                                      "traceback.print_exc"):
                    return True
            if (handler.name and isinstance(n, ast.Name)
                    and n.id == handler.name
                    and isinstance(n.ctx, ast.Load)):
                return True
        return False

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        for handler in ctx.walk(ast.ExceptHandler):
            if not self._names_transport_error(handler.type):
                continue
            if self._is_observed(handler):
                continue
            yield self.violation(
                ctx, handler,
                "TransportError swallowed with no log, metric, or "
                "result — count it (RPC_FAILURES / a repair counter), log "
                "via logging.getLogger('weaviate_tpu.cluster'), or let it "
                "propagate",
                severity=SEV_CRITICAL,
            )


# ---------------------------------------------------------------------------
# 4c. unbounded-queue


class UnboundedQueue(Rule):
    id = "unbounded-queue"
    description = (
        "queue.Queue()/collections.deque() constructed without a "
        "maxsize/maxlen in the cross-thread serving path"
    )
    rationale = (
        "The serving QoS contract is that overload is SHED, never "
        "silently queued: an unbounded queue handed between threads in "
        "serving/, api/, or cluster/ is exactly the invisible backlog "
        "that turns a traffic spike into unbounded p99 and an OOM. "
        "Bound it (maxsize/maxlen), or suppress with a reason stating "
        "the invariant that bounds it externally."
    )

    _DIRS = ("weaviate_tpu/serving/", "weaviate_tpu/api/",
             "weaviate_tpu/cluster/")
    # constructor -> index of the positional bound argument
    _QUEUES = {"queue.Queue": 0, "queue.LifoQueue": 0,
               "queue.PriorityQueue": 0, "multiprocessing.Queue": 0}
    _DEQUES = {"collections.deque": 1}
    _NEVER_BOUNDED = frozenset({"queue.SimpleQueue"})
    _FROM_MODULES = {
        "queue": {"Queue": "queue.Queue", "LifoQueue": "queue.LifoQueue",
                  "PriorityQueue": "queue.PriorityQueue",
                  "SimpleQueue": "queue.SimpleQueue"},
        "collections": {"deque": "collections.deque"},
        "multiprocessing": {"Queue": "multiprocessing.Queue"},
    }

    def _bound_names(self, ctx) -> dict:
        """name-as-bound-in-file -> canonical ctor (from-imports only;
        dotted calls resolve through dotted_name directly)."""
        bound: dict[str, str] = {}
        for node in ctx.walk(ast.ImportFrom):
            table = self._FROM_MODULES.get(node.module or "")
            if not table:
                continue
            for a in node.names:
                if a.name in table:
                    bound[a.asname or a.name] = table[a.name]
        return bound

    @staticmethod
    def _is_bounded(call: ast.Call, pos: int) -> bool:
        """A bound exists unless the arg is absent, or a constant 0/None/
        negative (queue.Queue(0), Queue(maxsize=-1), and
        deque(maxlen=None) all mean unbounded, spelled loudly)."""
        kw_name = "maxlen" if pos == 1 else "maxsize"
        arg: Optional[ast.AST] = None
        if len(call.args) > pos:
            arg = call.args[pos]
        for kw in call.keywords:
            if kw.arg == kw_name:
                arg = kw.value
        if arg is None:
            return False
        if isinstance(arg, ast.Constant) and arg.value in (0, None):
            return False
        # -N parses as UnaryOp(USub, Constant(N)): Queue(maxsize=-1)
        if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub) \
                and isinstance(arg.operand, ast.Constant):
            return False
        return True

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        bound = self._bound_names(ctx)
        for node in ctx.walk(ast.Call):
            dn = dotted_name(node.func)
            if dn is None:
                continue
            canonical = bound.get(dn, dn)
            if canonical in self._NEVER_BOUNDED:
                yield self.violation(
                    ctx, node,
                    f"{dn}() has no capacity bound at all — use "
                    "queue.Queue(maxsize=...) so overload backpressures "
                    "instead of accumulating",
                )
            elif canonical in self._QUEUES:
                if not self._is_bounded(node, self._QUEUES[canonical]):
                    yield self.violation(
                        ctx, node,
                        f"{dn}() without maxsize= — an unbounded "
                        "cross-thread queue; bound it or state the "
                        "external invariant in a suppression reason",
                    )
            elif canonical in self._DEQUES:
                if not self._is_bounded(node, self._DEQUES[canonical]):
                    yield self.violation(
                        ctx, node,
                        f"{dn}() without maxlen= — an unbounded deque in "
                        "the serving path; bound it or state the external "
                        "invariant in a suppression reason",
                    )


# ---------------------------------------------------------------------------
# 4d. host-beam-fallback-unproven


class HostBeamFallbackUnproven(Rule):
    id = "host-beam-fallback-unproven"
    description = (
        "except-handler that permanently disables a device beam (sets a "
        "*beam* attribute to None) without incrementing a fallback counter"
    )
    rationale = (
        "The device-beam latch is deliberate: a kernel that never lowered "
        "on this backend disables itself and every future search silently "
        "downgrades to per-hop host round trips. The disable LOG LINE "
        "scrolls away in minutes while dashboards keep reporting healthy "
        "QPS at 10-100x worse latency. Any `_beam_proven`-style latch "
        "path must therefore also record the event on a counter "
        "(weaviate_tpu_device_beam_fallback_total) so the degradation is "
        "observable and alertable — logging alone does not count."
    )

    _DIRS = ("weaviate_tpu/index/", "weaviate_tpu/ops/")
    _METRIC_ATTRS = frozenset({"inc", "observe"})

    @staticmethod
    def _beam_disable(handler: ast.ExceptHandler) -> Optional[ast.Assign]:
        """The assignment that latches a beam off (sets a *beam* name or
        attribute to None), or None. The violation anchors HERE so the
        allow-comment sits next to the latch, not the except line."""
        for n in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            if not isinstance(n, ast.Assign):
                continue
            if not (isinstance(n.value, ast.Constant)
                    and n.value.value is None):
                continue
            for t in n.targets:
                name = (t.attr if isinstance(t, ast.Attribute)
                        else t.id if isinstance(t, ast.Name) else "")
                if "beam" in name:
                    return n
        return None

    def _counts_fallback(self, handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in self._METRIC_ATTRS:
                return True
        return False

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        for handler in ctx.walk(ast.ExceptHandler):
            disable = self._beam_disable(handler)
            if disable is None:
                continue
            if self._counts_fallback(handler):
                continue
            yield self.violation(
                ctx, disable,
                "device-beam fallback latch without a counter — a "
                "permanent host-walk downgrade must increment "
                "DEVICE_BEAM_FALLBACK (or another .inc()/.observe() "
                "instrument) so the degradation is observable, not just "
                "logged",
                severity=SEV_WARNING,
            )


# ---------------------------------------------------------------------------
# 4e. device-array-leak


class DeviceArrayLeak(Rule):
    id = "device-array-leak"
    description = (
        "discarded byte delta from a tiered-residency move "
        "(demote_device/promote_device/detach/attach/drop_device)"
    )
    rationale = (
        "The tiering primitives return the HBM bytes they released or "
        "charged, and the HbmAccountant ledger is only honest if every "
        "caller propagates that delta (or refreshes the absolute "
        "footprint). A bare-statement call throws the delta away: the "
        "arrays moved but the budget ledger did not, so the controller "
        "either keeps evicting tenants that already left HBM or lets "
        "real residency grow past the budget unseen."
    )

    # demote/promote/drop are tiering-specific names: flag anywhere in
    # the package. detach/attach are generic — only the store/code-plane
    # layers use them with the accountant contract.
    _ALWAYS = frozenset({"demote_device", "promote_device", "drop_device"})
    _STORE_ONLY = frozenset({"detach", "attach"})
    _STORE_DIRS = ("weaviate_tpu/index/", "weaviate_tpu/compression/",
                   "weaviate_tpu/tiering/", "weaviate_tpu/ops/")

    def check(self, ctx) -> Iterator[Violation]:
        if not ctx.rel_path.startswith("weaviate_tpu/"):
            return
        in_store_layer = _path_in(ctx.rel_path, self._STORE_DIRS)
        for node in ctx.walk(ast.Expr):
            call = node.value
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)):
                continue
            meth = call.func.attr
            if meth in self._ALWAYS or (in_store_layer
                                        and meth in self._STORE_ONLY):
                yield self.violation(
                    ctx, node,
                    f"result of {meth}() discarded — the returned HBM "
                    "byte delta must reach the tiering accountant "
                    "(assign it, return it, or re-charge the absolute "
                    "footprint via note_shard_open/charge)",
                    severity=SEV_ERROR,
                )


# ---------------------------------------------------------------------------
# 4f. host-loop-over-mesh


class HostLoopOverMesh(Rule):
    id = "host-loop-over-mesh"
    description = (
        "Python for-loop over mesh devices (mesh.devices / jax.devices()) "
        "whose body issues per-device dispatches"
    )
    rationale = (
        "The mesh serving contract is ONE SPMD program per batch "
        "(shard_map + on-device cross-shard merge, ops/device_beam.py + "
        "parallel/sharded_search.py): a host loop that dispatches work "
        "per device serializes N round trips behind the Python thread, "
        "re-introducing exactly the scatter-gather the fused mesh walk "
        "exists to delete. Enumerating devices for metadata (counts, "
        "placement tables) is fine — only loops that DISPATCH per "
        "device are flagged. Rewrite as a shard_map/psum program, or "
        "suppress with the invariant that makes the loop cold."
    )

    _DIRS = ("weaviate_tpu/parallel/", "weaviate_tpu/index/")
    _DEVICE_ATTRS = frozenset({"devices", "local_devices"})

    def _iterates_devices(self, it: ast.AST) -> bool:
        """Whether the loop's iterable mentions a device enumeration:
        ``mesh.devices`` (and .flat/.ravel() views), ``jax.devices()``,
        ``jax.local_devices()``, or enumerate(...) of any of those."""
        for n in ast.walk(it):
            if isinstance(n, ast.Attribute) and n.attr in self._DEVICE_ATTRS:
                return True
        return False

    def _dispatch_in_body(self, node, ctx) -> Optional[ast.Call]:
        for call in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if isinstance(call, ast.Call) and is_dispatch_call(call, ctx):
                return call
        return None

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        for node in ctx.walk(ast.For, ast.AsyncFor):
            if not self._iterates_devices(node.iter):
                continue
            call = self._dispatch_in_body(node, ctx)
            if call is None:
                continue
            dn = dotted_name(call.func)
            yield self.violation(
                ctx, node,
                f"for-loop over mesh devices dispatches {dn}(...) per "
                "device — N serialized round trips instead of one SPMD "
                "program; use shard_map with an on-device merge "
                "(parallel/sharded_search.py, ops/topk."
                "merge_across_shards)",
                severity=SEV_ERROR,
            )


# ---------------------------------------------------------------------------
# 4g. host-loop-over-targets


class HostLoopOverTargets(Rule):
    id = "host-loop-over-targets"
    description = (
        "Python for-loop over named-vector targets whose body issues "
        "per-target device dispatches or host merges"
    )
    rationale = (
        "The multi-target serving contract is ONE fused device dispatch "
        "per batch (ops/device_beam.py device_multi_search: per-target "
        "walks + cross-scoring + join + top-k inside one jitted "
        "program, docs/multitarget.md): a host loop that walks or "
        "merges per target pays T dispatch round trips and a host-side "
        "join, exactly the scatter the fused program deletes. "
        "Enumerating targets for metadata (counts, plane accounting, "
        "config plumbing) is fine — only loops that DISPATCH or run a "
        "per-target search/merge are flagged. Route through "
        "Shard.multi_target_search, or suppress with the invariant "
        "that makes the loop cold (the host parity oracle lives in "
        "core/, outside this rule's scope, on purpose)."
    )

    _DIRS = ("weaviate_tpu/index/", "weaviate_tpu/query/",
             "weaviate_tpu/ops/")
    _TARGET_NAMES = frozenset({"targets", "target_vectors",
                               "named_vectors", "_vector_indexes"})
    _MERGE_CALLS = frozenset({"vector_search", "vector_search_batch",
                              "device_beam_search",
                              "combine_multi_target"})

    def _iterates_targets(self, it: ast.AST) -> bool:
        """Whether the loop's iterable mentions a target enumeration:
        ``targets`` / ``target_vectors`` / ``named_vectors`` /
        ``_vector_indexes`` as a name or attribute (including .items()/
        .values() views and enumerate(...) of any of those)."""
        for n in ast.walk(it):
            if isinstance(n, ast.Name) and n.id in self._TARGET_NAMES:
                return True
            if isinstance(n, ast.Attribute) \
                    and n.attr in self._TARGET_NAMES:
                return True
        return False

    def _per_target_work(self, node, ctx) -> Optional[ast.Call]:
        for call in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if not isinstance(call, ast.Call):
                continue
            if is_dispatch_call(call, ctx):
                return call
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in self._MERGE_CALLS:
                return call
            if isinstance(call.func, ast.Name) \
                    and call.func.id in self._MERGE_CALLS:
                return call
        return None

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        for node in ctx.walk(ast.For, ast.AsyncFor):
            if not self._iterates_targets(node.iter):
                continue
            call = self._per_target_work(node, ctx)
            if call is None:
                continue
            dn = dotted_name(call.func)
            yield self.violation(
                ctx, node,
                f"for-loop over named-vector targets runs {dn}(...) per "
                "target — T serialized walks + a host join instead of "
                "the one fused multi-target dispatch; route through "
                "Shard.multi_target_search (ops/device_beam."
                "device_multi_search)",
                severity=SEV_ERROR,
            )


# ---------------------------------------------------------------------------
# 5. lock-across-device-call


class LockAcrossDeviceCall(Rule):
    id = "lock-across-device-call"
    description = (
        "jax/ops device call issued while holding a threading lock"
    )
    rationale = (
        "Device dispatch under a Python lock serializes every serving "
        "thread behind one device round trip; snapshot state under the "
        "lock, release it, then dispatch."
    )

    def _lock_items(self, node) -> list:
        names = []
        for item in node.items:
            dn = dotted_name(item.context_expr)
            if dn and "lock" in dn.lower():
                names.append(dn)
        return names

    def check(self, ctx) -> Iterator[Violation]:
        for node in ctx.walk(ast.With, ast.AsyncWith):
            locks = self._lock_items(node)
            if not locks:
                continue
            for call in ast.walk(ast.Module(body=node.body, type_ignores=[])):
                if not isinstance(call, ast.Call) \
                        or not is_dispatch_call(call, ctx):
                    continue
                yield self.violation(
                    ctx, call,
                    f"{dotted_name(call.func)}(...) dispatched while "
                    f"holding {', '.join(locks)} — move device work "
                    "outside the critical section",
                    severity=SEV_WARNING,
                )


# ---------------------------------------------------------------------------
# 5b. device-feed-under-lock


class DeviceFeedUnderLock(Rule):
    id = "device-feed-under-lock"
    description = (
        "vector-index feed (_feed_index / add_batch) issued while a lock "
        "is held in core/ write-path code"
    )
    rationale = (
        "The ingest pipeline's contract (docs/ingest.md): the lock-held "
        "critical section of the write path is DURABILITY ONLY — WAL/"
        "delta append, object + inverted + id-map writes, and the queue "
        "chunk push. Feeding the vector index is device work (graph "
        "construction included); doing it in-lock reintroduces the "
        "write-path convoy PR 15 removed, where one writer's device "
        "build queues every other writer and reader on the shard. Feed "
        "in a queue drain window after releasing the lock instead."
    )

    _FEEDS = ("add_batch", "add_batch_multi")

    def _is_feed(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name) and f.id == "_feed_index":
            return True
        return isinstance(f, ast.Attribute) and f.attr in self._FEEDS

    def _held_context(self, ctx, call: ast.Call) -> Optional[str]:
        """The lock context a feed call executes under: a lexical ``with
        <something named *lock*>:`` ancestor, or an enclosing function
        named ``*_locked`` (the repo convention for 'caller holds the
        lock' — the convoy is the same whether the acquisition is
        visible in this function or in its caller)."""
        for parent, field in ctx.ancestry(call):
            if isinstance(parent, (ast.With, ast.AsyncWith)) \
                    and field == "body":
                for item in parent.items:
                    dn = dotted_name(item.context_expr)
                    if dn and "lock" in dn.lower():
                        return dn
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and field == "body" and parent.name.endswith("_locked"):
                return f"{parent.name}() [lock held by caller, by the " \
                       "*_locked naming convention]"
        return None

    def check(self, ctx) -> Iterator[Violation]:
        if not ctx.rel_path.startswith("weaviate_tpu/core/"):
            return
        for call in ctx.walk(ast.Call):
            if not self._is_feed(call):
                continue
            held = self._held_context(ctx, call)
            if held is None:
                continue
            fn = call.func
            name = fn.id if isinstance(fn, ast.Name) else fn.attr
            yield self.violation(
                ctx, call,
                f"{name}(...) feeds a vector index while {held} is held "
                "— the write path's critical section is durability only; "
                "push a queue chunk and feed in a drain window after "
                "releasing the lock (docs/ingest.md)",
            )


# ---------------------------------------------------------------------------
# 6. float64-literal-drift


class Float64LiteralDrift(Rule):
    id = "float64-literal-drift"
    description = (
        "jnp array constructor fed a Python float literal without an "
        "explicit dtype in kernel files"
    )
    rationale = (
        "Kernel math is bf16-in / fp32-accumulate by contract; an undtyped "
        "jnp.array(0.5) defaults to float32 (float64 under x64) and "
        "silently widens whatever it touches, bloating VMEM tiles."
    )

    # constructors where the dtype may also arrive positionally at index N
    _CTORS = {
        "jnp.array": 1, "jnp.asarray": 1, "jnp.full": 2,
        "jnp.linspace": 5, "jnp.arange": 3, "jnp.ones": 1, "jnp.zeros": 1,
    }

    def _has_float_literal(self, node: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Constant) and isinstance(n.value, float)
            for n in ast.walk(node)
        )

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, KERNEL_DIRS):
            return
        for node in ctx.walk(ast.Call):
            dn = dotted_name(node.func)
            if dn not in self._CTORS:
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if len(node.args) > self._CTORS[dn]:
                continue  # dtype passed positionally
            value_args = node.args[: self._CTORS[dn]]
            if any(self._has_float_literal(a) for a in value_args):
                yield self.violation(
                    ctx, node,
                    f"{dn}(<float literal>) without dtype= — pin the kernel "
                    "dtype explicitly (jnp.float32/bf16)",
                )


# ---------------------------------------------------------------------------
# 6b. lockwitness-in-kernel


class LockwitnessInKernel(Rule):
    id = "lockwitness-in-kernel"
    description = (
        "lockwitness (the runtime lock-order witness) referenced in "
        "kernel files or inside a jit-decorated function"
    )
    rationale = (
        "The witness wraps Python locks to record acquisition order; it "
        "must stay strictly host-side. A reference inside "
        "weaviate_tpu/ops/ or in a jitted function body would put "
        "witness bookkeeping on the trace — at best a retrace per "
        "install, at worst host callbacks inside the compiled program. "
        "Instrument the callers, never the kernels."
    )

    _NAMES = ("lockwitness",)

    def _mentions_witness(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self._NAMES:
                return True
            if isinstance(n, ast.Attribute) and n.attr in self._NAMES:
                return True
            if isinstance(n, (ast.Import, ast.ImportFrom)):
                mod = getattr(n, "module", "") or ""
                if "lockwitness" in mod or any(
                        "lockwitness" in a.name for a in n.names):
                    return True
        return False

    def check(self, ctx) -> Iterator[Violation]:
        if _path_in(ctx.rel_path, KERNEL_DIRS):
            for node in ctx.walk(ast.Import, ast.ImportFrom, ast.Name,
                                 ast.Attribute):
                if self._mentions_witness(node):
                    yield self.violation(
                        ctx, node,
                        "lockwitness referenced in a kernel file — the "
                        "witness is host-side instrumentation and must "
                        "never reach ops/ (wrap the caller's lock, not "
                        "the kernel)",
                    )
                    return  # one finding per file is enough
            return
        if not ctx.rel_path.startswith("weaviate_tpu/"):
            return
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            if not any(_decorator_is_jit(d) for d in fn.decorator_list):
                continue
            if self._mentions_witness(
                    ast.Module(body=fn.body, type_ignores=[])):
                yield self.violation(
                    ctx, fn,
                    f"jit-decorated {fn.name}() references lockwitness — "
                    "witness bookkeeping inside a traced function ends "
                    "up in the compiled program; instrument outside the "
                    "jit boundary",
                )


# ---------------------------------------------------------------------------
# 6c. tracer-in-kernel


class TracerInKernel(Rule):
    id = "tracer-in-kernel"
    description = (
        "tracer/span references in kernel files or inside a "
        "jit-decorated function"
    )
    rationale = (
        "Spans are host-side bookkeeping; a ``span.__enter__`` inside a "
        "traced-out function runs ONCE at trace time and never again — "
        "the span silently reports nothing (or worse, one stale "
        "compile-time measurement) while looking instrumented. A tracer "
        "reference in weaviate_tpu/ops/ or in a jitted body is therefore "
        "silent wrongness, not overhead. Instrument the dispatch SITE "
        "(index/, serving/, cluster/), never the kernel."
    )

    _NAMES = ("TRACER", "tracing")

    def _mentions_tracer(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self._NAMES:
                return True
            if isinstance(n, ast.Attribute) and n.attr in self._NAMES:
                return True
            if isinstance(n, (ast.Import, ast.ImportFrom)):
                mod = getattr(n, "module", "") or ""
                if "monitoring.tracing" in mod or mod == "tracing" or any(
                        a.name == "tracing" or a.name == "TRACER"
                        or a.name.endswith(".tracing") for a in n.names):
                    return True
        return False

    def check(self, ctx) -> Iterator[Violation]:
        if _path_in(ctx.rel_path, KERNEL_DIRS):
            for node in ctx.walk(ast.Import, ast.ImportFrom, ast.Name,
                                 ast.Attribute):
                if self._mentions_tracer(node):
                    yield self.violation(
                        ctx, node,
                        "tracer referenced in a kernel file — spans are "
                        "host-side and a span in traced code reports "
                        "nothing; instrument the dispatch site, never "
                        "ops/",
                    )
                    return  # one finding per file is enough
            return
        if not ctx.rel_path.startswith("weaviate_tpu/"):
            return
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            if not any(_decorator_is_jit(d) for d in fn.decorator_list):
                continue
            if self._mentions_tracer(
                    ast.Module(body=fn.body, type_ignores=[])):
                yield self.violation(
                    ctx, fn,
                    f"jit-decorated {fn.name}() references the tracer — "
                    "a span __enter__ in a traced-out function runs at "
                    "trace time only and measures nothing; span the "
                    "caller outside the jit boundary",
                )


# ---------------------------------------------------------------------------
# 6d. module-hook-host-sync


class ModuleHookHostSync(Rule):
    id = "module-hook-host-sync"
    description = (
        "host sync (np.asarray/.item()/host callbacks) inside a device "
        "module hook (modules/device/ score/__call__) or a rerank-stage "
        "function in ops/"
    )
    rationale = (
        "Device module hooks (``DeviceRerankModule.score``) and the "
        "rerank-stage functions in ops/ are traced INSIDE the fused "
        "search program — the whole point of the module tier is that "
        "rerank costs one dispatch, not a host round-trip. A "
        "``np.asarray``/``.item()`` there either breaks tracing "
        "outright or (via a callback) reintroduces the per-query host "
        "sync the tier exists to remove. Host-side scoring belongs in "
        "``host_score`` (the fallback tier), never in the traced hook."
    )

    MODULE_DIR = "weaviate_tpu/modules/device/"
    OPS_DIR = "weaviate_tpu/ops/"
    HOOK_NAMES = ("score", "__call__")
    # host-callback entry points: these smuggle host Python back into
    # the compiled program even when they trace successfully
    _CALLBACK_ATTRS = frozenset({
        "device_get", "pure_callback", "io_callback",
        "block_until_ready", "item",
    })
    _HOST_ROOTS = ("np", "numpy")

    def _sync_calls(self, fn: ast.AST):
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            dn = dotted_name(n.func)
            if dn is not None and dn.split(".")[0] in self._HOST_ROOTS:
                yield n, f"{dn}(...) is a host-side numpy call"
                continue
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr in self._CALLBACK_ATTRS:
                yield n, (f".{n.func.attr}() syncs (or calls back to) "
                          "the host")

    def check(self, ctx) -> Iterator[Violation]:
        if ctx.rel_path.startswith(self.MODULE_DIR):
            targets = [
                fn for fn in ctx.walk(ast.FunctionDef,
                                      ast.AsyncFunctionDef)
                if fn.name in self.HOOK_NAMES
            ]
            where = "device module hook"
        elif ctx.rel_path.startswith(self.OPS_DIR):
            targets = [
                fn for fn in ctx.walk(ast.FunctionDef,
                                      ast.AsyncFunctionDef)
                if "rerank" in fn.name
            ]
            where = "rerank-stage function"
        else:
            return
        for fn in targets:
            for node, what in self._sync_calls(fn):
                yield self.violation(
                    ctx, node,
                    f"{what} inside {where} {fn.name}() — the hook is "
                    "traced into the fused search program; host-side "
                    "math belongs in host_score (the fallback tier)",
                )


# ---------------------------------------------------------------------------
# 7. suppression-missing-reason (meta-rule, emitted by the engine)


class UnverifiedRemoteDelete(Rule):
    id = "unverified-remote-delete"
    description = (
        "delete of a local segment set or remote blob in backup/ or "
        "tiering/ with no manifest/digest verification earlier in the "
        "same function"
    )
    rationale = (
        "The cold tier and the backup store are the LAST copy of data "
        "once the local files go: the offload contract is verify-then-"
        "delete-local, and retention sweeps must re-verify a manifest "
        "before garbage-collecting anything it might reference. A "
        "delete (remote `.delete(...)` on a store/client handle, or a "
        "local os.remove/os.unlink/shutil.rmtree) that no verification "
        "call precedes is exactly the shape of a data-loss bug chaos "
        "testing keeps finding. Call something whose name carries "
        "verify/digest/sha256/checksum first (verify_uploaded, "
        "verify_backup, hexdigest, ...), or route the deletion through "
        "a dedicated ``*delete*`` helper that owns its safety contract. "
        "Scratch targets (tmp/staging/partial/orphan names) are exempt."
    )

    _DIRS = ("weaviate_tpu/backup/", "weaviate_tpu/tiering/")
    # receiver tails that look like a blob-store / object-store handle
    _REMOTE_RECV = ("client", "store", "blob", "backend", "bucket", "s3",
                    "inner")
    _LOCAL_FNS = frozenset({"os.remove", "os.unlink", "shutil.rmtree",
                            "_os.remove", "_os.unlink", "_shutil.rmtree"})
    _VERIFY_MARKS = ("verify", "digest", "sha256", "checksum")
    _SCRATCH_MARKS = ("tmp", "temp", "stag", "partial", "orphan")

    def _is_remote_delete(self, call: ast.Call) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "delete"):
            return False
        recv = dotted_name(f.value) or ""
        tail = recv.rsplit(".", 1)[-1].lower()
        return any(m in tail for m in self._REMOTE_RECV)

    def _is_local_delete(self, call: ast.Call) -> bool:
        return dotted_name(call.func) in self._LOCAL_FNS

    def _is_scratch(self, call: ast.Call) -> bool:
        """Deleting a tmp/staging/partial/orphan target is cleanup, not
        data destruction — judged by the names in the argument subtree."""
        words = []
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(a):
                if isinstance(n, ast.Name):
                    words.append(n.id.lower())
                elif isinstance(n, ast.Attribute):
                    words.append(n.attr.lower())
                elif isinstance(n, ast.Constant) and isinstance(
                        n.value, str):
                    words.append(n.value.lower())
        return any(m in w for w in words for m in self._SCRATCH_MARKS)

    def _has_verify_mark(self, node: ast.AST) -> bool:
        name = None
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else dotted_name(f) or "")
        return bool(name) and any(m in name.lower()
                                  for m in self._VERIFY_MARKS)

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        for call in ctx.walk(ast.Call):
            remote = self._is_remote_delete(call)
            if not remote and not self._is_local_delete(call):
                continue
            fns = ctx.enclosing_functions(call)
            if not fns:
                continue  # module level: import-time deletes don't occur
            fn = fns[0]
            # a function that IS the deletion primitive (``delete``,
            # ``delete_partial_backup``…) owns its own contract; the rule
            # polices call sites
            if "delete" in fn.name.lower():
                continue
            if not remote and self._is_scratch(call):
                continue
            verified = any(
                self._has_verify_mark(n) and n is not call
                and getattr(n, "lineno", 1 << 30) <= call.lineno
                for n in ast.walk(fn))
            if verified:
                continue
            kind = "remote blob" if remote else "local segment"
            yield self.violation(
                ctx, call,
                f"{kind} delete in {fn.name}() with no preceding "
                "manifest/digest verification — verify-then-delete, or "
                "move it into a dedicated *delete* helper",
                severity=SEV_ERROR)


class SingletonCycleWithoutLeaderCheck(Rule):
    id = "singleton-cycle-without-leader-check"
    description = (
        "cycle-runner-registered function (or conventional tick/*_cycle "
        "entrypoint) in cluster/ that submits raft commands or calls "
        "rebalancer join/drain without consulting raft leadership"
    )
    rationale = (
        "Background cycles run on EVERY node, but a policy loop that "
        "journals decisions or mutates membership must be a raft-leader "
        "singleton: two nodes acting on the same stale pressure view "
        "provision twice, drain the wrong node, or double-journal one "
        "decision — split-brain actuation, the exact bug class the "
        "autoscaler introduces (cluster/autoscale.py gates its tick on "
        "``raft.is_leader()`` before reading a single signal). The rule "
        "covers functions registered on a ``*.cycles.register(...)`` "
        "runner in the same file plus the conventional entrypoint names "
        "(``tick``, ``*_cycle``), and follows same-file helper calls — "
        "an actuation laundered through one private helper is as "
        "dangerous as a direct one. Consult ``is_leader`` (or "
        "``.leader()``) in the entrypoint before the actuation, or in a "
        "helper on the path to it."
    )

    _DIRS = ("weaviate_tpu/cluster/",)
    _MAX_DEPTH = 5

    @staticmethod
    def _is_actuation(call: ast.Call) -> bool:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return False
        recv = (dotted_name(f.value) or "").lower()
        if f.attr == "submit" and "raft" in recv:
            return True
        return f.attr in ("join", "drain") and "rebalancer" in recv

    @staticmethod
    def _consults_leadership(node: ast.AST) -> list[int]:
        """Line numbers of leadership consults in the subtree."""
        out = []
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr == "is_leader":
                out.append(n.lineno)
            elif isinstance(n, ast.Name) and n.id == "is_leader":
                out.append(n.lineno)
            elif (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "leader"):
                out.append(n.lineno)
        return out

    @staticmethod
    def _callee_names(fn: ast.AST) -> list[str]:
        """Bare names of same-file-resolvable callees: plain calls and
        ``self.<helper>(...)`` method calls."""
        names = []
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if isinstance(f, ast.Name):
                names.append(f.id)
            elif isinstance(f, ast.Attribute):
                recv = dotted_name(f.value) or ""
                if recv == "self" or recv.startswith("self."):
                    names.append(f.attr)
        return names

    def _registered_fns(self, ctx, fn_map: dict) -> dict:
        """Candidate entrypoints: {ast node -> report node}. Collects
        functions handed to a ``*.cycles.register(...)`` call (by name
        for defs, directly for lambdas) plus the conventional names."""
        out: dict = {}
        for call in ctx.walk(ast.Call):
            f = call.func
            if not (isinstance(f, ast.Attribute) and f.attr == "register"):
                continue
            recv = (dotted_name(f.value) or "").lower()
            if "cycles" not in recv:
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            for a in args:
                if isinstance(a, ast.Lambda):
                    out[a] = call
                elif isinstance(a, ast.Attribute) and a.attr in fn_map:
                    out[fn_map[a.attr]] = fn_map[a.attr]
                elif isinstance(a, ast.Name) and a.id in fn_map:
                    out[fn_map[a.id]] = fn_map[a.id]
        for name, fn in fn_map.items():
            if name == "tick" or name.endswith("_cycle"):
                out.setdefault(fn, fn)
        return out

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        fn_map = {fn.name: fn for fn in ctx.walk(ast.FunctionDef)}

        def reach(fn: ast.AST, depth: int, seen: set) -> tuple:
            """(direct actuation linenos, any reachable actuation,
            any reachable-helper leadership consult)."""
            direct = [c.lineno for c in ast.walk(fn)
                      if isinstance(c, ast.Call) and self._is_actuation(c)]
            any_act = bool(direct)
            helper_consult = False
            if depth < self._MAX_DEPTH:
                for name in self._callee_names(fn):
                    callee = fn_map.get(name)
                    if callee is None or callee in seen:
                        continue
                    seen.add(callee)
                    _, act, consult = reach(callee, depth + 1, seen)
                    any_act = any_act or act
                    helper_consult = (helper_consult or consult
                                      or bool(self._consults_leadership(
                                          callee)))
            return direct, any_act, helper_consult

        for fn, report_at in self._registered_fns(ctx, fn_map).items():
            direct, any_act, helper_consult = reach(fn, 0, {fn})
            if not any_act:
                continue
            own = self._consults_leadership(fn)
            first_act = min(direct) if direct else (1 << 30)
            # a direct actuation needs a consult BEFORE it; actuation
            # buried in helpers is covered by any consult on the path
            consulted = (any(ln <= first_act for ln in own)
                         or (not direct and bool(own))
                         or helper_consult)
            if consulted:
                continue
            name = getattr(fn, "name", "<lambda>")
            yield self.violation(
                ctx, report_at,
                f"cycle entrypoint {name}() submits raft commands or "
                "calls join/drain without consulting raft leadership "
                "first — background cycles run on every node; gate the "
                "actuation on is_leader() or it runs split-brain",
                severity=SEV_ERROR)


class SuppressionMissingReason(Rule):
    id = "suppression-missing-reason"
    description = (
        "graftlint allow-comment without a reason= — suppressions must "
        "say why the hazard is acceptable"
    )
    rationale = (
        "An unexplained suppression is indistinguishable from a silenced "
        "bug; the reason is the review artifact."
    )

    def check(self, ctx) -> Iterator[Violation]:
        for line_no, rules in sorted(ctx.bad_suppressions.items()):
            yield Violation(
                rule=self.id,
                path=ctx.rel_path,
                line=line_no,
                col=0,
                severity=SEV_ERROR,
                message=(
                    f"allow[{','.join(sorted(rules))}] has no reason=; the "
                    "suppression is ignored until one is given"
                ),
                symbol="<module>",
                snippet=ctx.line_snippet(line_no),
            )


# ---------------------------------------------------------------------------
# 7b. unwarmed-jit-program


class UnwarmedJitProgram(Rule):
    id = "unwarmed-jit-program"
    description = (
        "module-level jax.jit entry point in ops/ or parallel/ not "
        "registered in the prewarm manifest "
        "(weaviate_tpu/utils/prewarm.py MANIFEST)"
    )
    rationale = (
        "The prewarm driver compiles the shape-bucket lattice of every "
        "registered serving program at boot / tenant promotion / "
        "rebalance warming, so restarted nodes answer their first query "
        "compile-free. A serving jit missing from the manifest silently "
        "re-opens the compile tax on the cold path. Register it in "
        "MANIFEST, or suppress with a reason for genuinely cold paths "
        "(construction-only programs compile during builds, not "
        "serving)."
    )

    SCOPES = ("weaviate_tpu/ops/", "weaviate_tpu/parallel/")
    # tests inject a manifest here; None = read the real tree's
    manifest_override: Optional[frozenset] = None
    _manifest_cache: Optional[frozenset] = None

    @classmethod
    def _manifest(cls) -> frozenset:
        if cls.manifest_override is not None:
            return cls.manifest_override
        if cls._manifest_cache is None:
            cls._manifest_cache = cls._load_manifest()
        return cls._manifest_cache

    @staticmethod
    def _load_manifest() -> frozenset:
        """String-literal keys of ``MANIFEST = {...}`` in prewarm.py,
        read from the AST — the registry must stay statically
        analyzable (no computed keys)."""
        import pathlib

        path = (pathlib.Path(__file__).resolve().parents[2]
                / "weaviate_tpu" / "utils" / "prewarm.py")
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            return frozenset()
        names = set()
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if not any(isinstance(t, ast.Name) and t.id == "MANIFEST"
                       for t in targets):
                continue
            if isinstance(value, ast.Dict):
                for key in value.keys:
                    if isinstance(key, ast.Constant) \
                            and isinstance(key.value, str):
                        names.add(key.value)
        return frozenset(names)

    def _module_dotted(self, rel_path: str) -> str:
        # weaviate_tpu/ops/distance.py -> ops.distance (matches the
        # manifest's dotted-under-weaviate_tpu key format)
        mod = rel_path[len("weaviate_tpu/"):]
        if mod.endswith(".py"):
            mod = mod[:-3]
        return mod.replace("/", ".")

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self.SCOPES):
            return
        manifest = self._manifest()
        mod = self._module_dotted(ctx.rel_path)
        for node in ctx.tree.body:
            name: Optional[str] = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_decorator_is_jit(d) for d in node.decorator_list):
                    name = node.name
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit_like(node.value) is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        name = t.id
                        break
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit_like(node.value) is not None \
                    and isinstance(node.target, ast.Name):
                name = node.target.id
            if name is None:
                continue
            program = f"{mod}.{name}"
            if program in manifest:
                continue
            yield self.violation(
                ctx, node,
                f"jit entry point {program!r} is not registered in the "
                "prewarm manifest (weaviate_tpu/utils/prewarm.py) — "
                "register it so the driver warms its shape buckets, or "
                "suppress with a reason if it never serves queries",
                severity=SEV_WARNING,
            )


# ---------------------------------------------------------------------------
# 8. whole-program concurrency rules (driven by tools/graftlint/
#    concurrency.py — the per-file check() is a no-op; the engine runs
#    the interprocedural pass once per tree and routes its findings
#    through the same suppression/baseline pipeline)


class WholeProgramRule(Rule):
    def check(self, ctx) -> Iterator[Violation]:
        return iter(())


class LockOrderCycle(WholeProgramRule):
    id = "lock-order-cycle"
    description = (
        "cycle in the interprocedural lock-order graph (potential "
        "deadlock), incl. self-deadlock on non-reentrant locks"
    )
    rationale = (
        "Two threads entering a lock-order cycle from different edges "
        "wedge forever — the PR 7 mesh-dispatch deadlock class. The "
        "order graph is built whole-program: holding L while calling a "
        "function that (transitively) acquires M is an L->M edge, so a "
        "cycle spanning three modules is as visible as a nested with."
    )


class BlockingUnderLock(WholeProgramRule):
    id = "blocking-under-lock"
    description = (
        "blocking operation (RPC send, sleep, Future.result, queue.get, "
        "foreign cv/event wait, callee's device dispatch) reachable "
        "while a lock is held"
    )
    rationale = (
        "A lock held across a wait turns every contending thread into a "
        "convoy behind one straggler, and held across an RPC it couples "
        "local liveness to a remote peer. Snapshot under the lock, "
        "release, then block. Interprocedural: the wait may be three "
        "calls deep."
    )


class UnlockedCollectiveDispatch(WholeProgramRule):
    id = "unlocked-collective-dispatch"
    description = (
        "collective-bearing mesh program dispatched on a path reachable "
        "without mesh_dispatch_lock held"
    )
    rationale = (
        "Collective SPMD programs (all_gather/psum rendezvous) must "
        "enqueue on every device in one total order; two concurrent "
        "dispatches can interleave per-device enqueues in opposite "
        "orders and deadlock at the rendezvous — found live in PR 7, "
        "enforced statically here. Wrap the dispatch in `with "
        "mesh_dispatch_lock():`."
    )


# ---------------------------------------------------------------------------
# 9. whole-program error-path / deadline rules (driven by tools/
#    graftlint/errorflow.py — same dispatch shape as the concurrency
#    rules above)


class UncheckedRpcReply(WholeProgramRule):
    id = "unchecked-rpc-reply"
    description = (
        "field access or truthiness-as-success on an RPC reply / fan-out "
        "queue payload / blob get that never flowed through _expect, an "
        "error-key check, or a registered validator"
    )
    rationale = (
        "An error reply is {'error': ...} — truthy, and .get() of any "
        "data key reads as missing/zero. PR 10's digest round treated "
        "exactly that as a verified-zero and could flip+drop objects on "
        "nothing; PR 16 swept the backup plane for the same shape. "
        "Taint is tracked whole-program (assignment, tuple unpack, "
        "queue put/get, helper returns) so a reply laundered through "
        "two helpers is as visible as a direct read. SEV_ERROR in "
        "cluster/, backup/, tiering/ — the planes where the bug class "
        "destroys data."
    )


class BudgetMintedInFlight(WholeProgramRule):
    id = "budget-minted-in-flight"
    description = (
        "fresh Deadline(...) constructed on a path reachable from the "
        "serving ingress set instead of threading _op_deadline/"
        "RequestContext"
    )
    rationale = (
        "A leg that mints its own budget outlives the request that "
        "paid for it: the client has timed out and retried while the "
        "orphan leg still holds locks and sockets — PR 16's backup-leg "
        "bug. The only sanctioned mints are the ingress itself (the "
        "function installing the RequestContext) and the _op_deadline "
        "fallback for non-serving callers."
    )


class BlockingCallWithoutDeadline(WholeProgramRule):
    id = "blocking-call-without-deadline"
    description = (
        "blocking call (queue.get, Future.result, event wait, socket "
        "send/recv, blob I/O) reachable from the serving ingress set "
        "with no deadline clamp on any path"
    )
    rationale = (
        "Unbounded blocking on a serving path turns one slow peer into "
        "a stuck worker thread; enough of them and the pool is gone — "
        "the class PR 3/PR 9/PR 11 fixed by hand three times. A call "
        "is clamped if it passes a timeout or the enclosing function "
        "threads deadline machinery (deadline/timeout parameter, "
        "_op_deadline, retrying_call, Deadline methods)."
    )


class UnplannedFilteredSearch(Rule):
    id = "unplanned-filtered-search"
    description = (
        "filtered search entry point that bypasses the cost-based "
        "planner, or materializes a full-corpus host mask without "
        "consulting the resident filter-plane store"
    )
    rationale = (
        "Filtered device search is routed by query/planner: plan() "
        "races exact-scan / filtered-beam / over-fetch from selectivity "
        "stats, and hot predicates serve from device-resident bitmap "
        "planes the dispatcher coalesces by (plane_id, version). A "
        "search path that takes an allow mask straight into the "
        "dispatcher re-introduces the unplanned walk the planner "
        "replaced (wrong plan at the selectivity extremes), and an "
        "inverted-index allow_list() materialization that never asks "
        "the plane store first pays a full-corpus mask build + device "
        "upload per query for predicates that already have a resident "
        "plane. Consult plan()/filter_planes, or suppress with the "
        "invariant that makes the bypass safe."
    )

    _DIRS = ("weaviate_tpu/index/", "weaviate_tpu/query/")
    _ALLOW_ARGS = frozenset({"allow", "allow_list"})
    _PLANNER_TOKENS = frozenset({
        "plan", "planner", "PlanStats", "expansion_budget",
    })

    @staticmethod
    def _tokens(fn: ast.AST) -> set:
        toks = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Name):
                toks.add(n.id)
            elif isinstance(n, ast.Attribute):
                toks.add(n.attr)
            elif isinstance(n, ast.ImportFrom) and n.module:
                toks.update(n.module.split("."))
        return toks

    def check(self, ctx) -> Iterator[Violation]:
        if not _path_in(ctx.rel_path, self._DIRS):
            return
        for fn in ctx.walk(ast.FunctionDef):
            args = fn.args
            names = {a.arg for a in (args.args + args.kwonlyargs
                                     + args.posonlyargs)}
            toks = None
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not isinstance(f, ast.Attribute):
                    continue
                if (f.attr == "search"
                        and isinstance(f.value, ast.Attribute)
                        and f.value.attr == "_dispatch"
                        and names & self._ALLOW_ARGS):
                    if toks is None:
                        toks = self._tokens(fn)
                    if not (toks & self._PLANNER_TOKENS):
                        yield self.violation(
                            ctx, node,
                            "filtered dispatcher search without a "
                            "planner decision — route the allow mask "
                            "through query.planner.plan() so the "
                            "exact/beam/over-fetch choice is costed "
                            "and traced",
                            severity=SEV_WARNING,
                        )
                elif f.attr == "allow_list":
                    if toks is None:
                        toks = self._tokens(fn)
                    if "filter_planes" not in toks:
                        yield self.violation(
                            ctx, node,
                            "full-corpus host mask materialized without "
                            "consulting the resident plane store — "
                            "lookup filter_planes first so hot "
                            "predicates serve from their device bitmap "
                            "instead of rebuilding the mask per query",
                            severity=SEV_WARNING,
                        )


ALL_RULES: tuple = (
    HostSyncInHotPath(),
    JitInLoop(),
    NonhashableStaticArg(),
    SwallowedException(),
    TransportErrorSwallowed(),
    UnboundedQueue(),
    HostBeamFallbackUnproven(),
    DeviceArrayLeak(),
    HostLoopOverMesh(),
    HostLoopOverTargets(),
    LockAcrossDeviceCall(),
    DeviceFeedUnderLock(),
    Float64LiteralDrift(),
    LockwitnessInKernel(),
    TracerInKernel(),
    ModuleHookHostSync(),
    LockOrderCycle(),
    BlockingUnderLock(),
    UnlockedCollectiveDispatch(),
    UncheckedRpcReply(),
    BudgetMintedInFlight(),
    BlockingCallWithoutDeadline(),
    UnwarmedJitProgram(),
    UnverifiedRemoteDelete(),
    SingletonCycleWithoutLeaderCheck(),
    UnplannedFilteredSearch(),
    SuppressionMissingReason(),
)

RULE_IDS = tuple(r.id for r in ALL_RULES)


def get_rules(select: Optional[Sequence[str]] = None) -> tuple:
    """Registry lookup; ``select=None`` means every rule."""
    if select is None:
        return ALL_RULES
    unknown = set(select) - set(RULE_IDS)
    if unknown:
        raise KeyError(f"unknown rule id(s): {sorted(unknown)}")
    return tuple(r for r in ALL_RULES if r.id in set(select))
