"""graftlint — AST-based static analysis guarding the TPU hot path.

Weaviate leans on ``go vet`` and the race detector to keep its serving
path honest; a JAX rebuild has failure modes those tools never see:
accidental device->host syncs in the distance hot loop, per-call jit
recompiles, dtype drift in kernels, and (shared with any distributed
DB) silently swallowed exceptions in replication paths. pytest catches
none of these — they surface as latency cliffs or quiet data loss.

graftlint walks the stdlib ``ast`` (no third-party deps), applies a
small registry of rules tuned to this codebase's real hazards, and
ratchets via a committed baseline: new violations fail tier-1, old
ones are tracked in ``baseline.json`` and burned down over time.

Usage::

    python -m tools.graftlint weaviate_tpu/
    python -m tools.graftlint weaviate_tpu/ --format json
    python -m tools.graftlint weaviate_tpu/ --fix-baseline

Per-site suppression (reason is mandatory)::

    x = np.asarray(dists)  # graftlint: allow[host-sync-in-hot-path] reason=final top-k materialization
"""

from tools.graftlint.engine import FileContext, lint_paths, lint_source
from tools.graftlint.rules import ALL_RULES, Rule, Violation, get_rules

__all__ = [
    "ALL_RULES",
    "FileContext",
    "Rule",
    "Violation",
    "get_rules",
    "lint_paths",
    "lint_source",
]

__version__ = "0.1.0"
