"""File walking, AST annotation, suppression parsing, rule dispatch.

The engine parses each file once, annotates every node with a parent
link + field name (so rules can ask "am I in a loop body?" vs "am I a
decorator?"), builds the import table rules need (what names this file
binds to ``weaviate_tpu.ops``), and collects ``# graftlint: allow[...]``
comments. Rules never re-read the file.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.graftlint.rules import (
    ALL_RULES,
    SEV_ERROR,
    Violation,
    get_rules,
)

_SNIPPET_MAX = 96

# graftlint: allow[rule-a,rule-b] reason=free text to end of line
_ALLOW_RE = re.compile(
    r"#\s*graftlint:\s*allow\[([A-Za-z0-9_\-, ]+)\]\s*(?:reason=(.*\S))?"
)

_SKIP_FILE_RE = re.compile(r"(_pb2\.py|_pb2_grpc\.py)$")


@dataclasses.dataclass
class Suppression:
    line: int
    rules: Set[str]
    reason: Optional[str]
    used: bool = False


class FileContext:
    """Everything a rule may ask about one parsed file."""

    def __init__(self, source: str, rel_path: str):
        self.rel_path = rel_path.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._parents: Dict[ast.AST, Tuple[Optional[ast.AST], str]] = {}
        self._annotate_parents()
        self.ops_imports: Set[str] = set()
        self.ops_aliases: Set[str] = set()
        self.device_imports: Set[str] = set()
        self.device_aliases: Set[str] = set()
        self._collect_imports()
        self.suppressions: List[Suppression] = []
        self.bad_suppressions: Dict[int, Set[str]] = {}
        self._collect_suppressions()

    # -- construction ---------------------------------------------------

    def _annotate_parents(self) -> None:
        self._parents[self.tree] = (None, "")
        stack = [self.tree]
        while stack:
            node = stack.pop()
            for field, value in ast.iter_fields(node):
                children = value if isinstance(value, list) else [value]
                for child in children:
                    if isinstance(child, ast.AST):
                        self._parents[child] = (node, field)
                        stack.append(child)

    _DEVICE_PKGS = ("weaviate_tpu.ops", "weaviate_tpu.parallel")

    def _collect_imports(self) -> None:
        """Names this file binds to device-dispatching code: ops/parallel
        function imports and module aliases. Rules use these to decide
        whether a call launches device work."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module == "weaviate_tpu":
                    for a in node.names:
                        if a.name in ("ops", "parallel"):
                            self.device_aliases.add(a.asname or a.name)
                            if a.name == "ops":
                                self.ops_aliases.add(a.asname or a.name)
                elif node.module.startswith(self._DEVICE_PKGS):
                    for a in node.names:
                        self.device_imports.add(a.asname or a.name)
                        if node.module.startswith("weaviate_tpu.ops"):
                            self.ops_imports.add(a.asname or a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith(self._DEVICE_PKGS):
                        alias = a.asname or a.name.split(".", 1)[0]
                        self.device_aliases.add(alias)
                        if a.name.startswith("weaviate_tpu.ops"):
                            self.ops_aliases.add(alias)

    def _collect_suppressions(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2)
            if reason is None or not reason.strip():
                self.bad_suppressions[i] = rules
                continue  # ignored until it carries a reason
            self.suppressions.append(
                Suppression(line=i, rules=rules, reason=reason.strip()))

    # -- queries used by rules ------------------------------------------

    def walk(self, *types) -> Iterable[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, types):
                yield node

    def parent_of(self, node: ast.AST) -> Tuple[Optional[ast.AST], str]:
        return self._parents.get(node, (None, ""))

    def ancestry(self, node: ast.AST) -> Iterable[Tuple[ast.AST, str]]:
        """Yield (ancestor, field-entered-through) from node outward."""
        cur = node
        while True:
            parent, field = self.parent_of(cur)
            if parent is None:
                return
            yield parent, field
            cur = parent

    def in_decorator(self, node: ast.AST) -> bool:
        return any(field == "decorator_list"
                   for _, field in self.ancestry(node))

    def in_loop(self, node: ast.AST) -> bool:
        """Inside the body/orelse of a for/while (comprehensions excluded —
        a comprehension is still one trace)."""
        for parent, field in self.ancestry(node):
            if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)) \
                    and field in ("body", "orelse"):
                return True
        return False

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        """Nearest function whose *body* owns this node (decorators and
        default-expressions execute in the outer scope), else the module."""
        for parent, field in self.ancestry(node):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and field == "body":
                return parent
        return self.tree

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first function chain; decorator position excluded."""
        chain = []
        for parent, field in self.ancestry(node):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if field == "decorator_list":
                    continue
                chain.append(parent)
        return chain

    def qualname(self, node: ast.AST) -> str:
        parts = []
        for parent, field in self.ancestry(node):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                if field != "decorator_list":
                    parts.append(parent.name)
        return ".".join(reversed(parts)) or "<module>"

    def line_snippet(self, line_no: int) -> str:
        if 1 <= line_no <= len(self.lines):
            return self.lines[line_no - 1].strip()[:_SNIPPET_MAX]
        return ""

    def snippet(self, node: ast.AST) -> str:
        return self.line_snippet(getattr(node, "lineno", 1))

    # -- suppression matching -------------------------------------------

    def is_suppressed(self, v: Violation) -> bool:
        """An allow-comment suppresses matching-rule violations on its own
        line and on the line directly below (comment-above style)."""
        for s in self.suppressions:
            if v.rule in s.rules and v.line in (s.line, s.line + 1):
                s.used = True
                return True
        return False


@dataclasses.dataclass
class LintResult:
    violations: List[Violation]
    suppressed: List[Violation]
    files_checked: int = 1
    parse_errors: List[Violation] = dataclasses.field(default_factory=list)
    # whole-program pass artifacts (None when not run): the
    # ConcurrencyModel carries the lock-order graph (for --format dot)
    # and the ErrorFlowModel the reply-taint graph (--format
    # errorflow-dot); both carry wall time + cache state for the JSON
    # report
    concurrency: Optional[object] = None
    errorflow: Optional[object] = None
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)


def repo_root() -> Path:
    """The repository this linter is vendored in (tools/graftlint/ -> repo).

    Anchors default path relativization so the prefix-scoped rules
    (hot-path, kernel, critical dirs) work no matter where the CLI is
    invoked from."""
    return Path(__file__).resolve().parents[2]


def _per_file_rules(ctx: FileContext, rules: Optional[Sequence[str]],
                    kept: List[Violation],
                    suppressed: List[Violation]) -> None:
    """Run the per-file registry rules on one context. Unused-suppression
    is NOT emitted here — the whole-program concurrency pass may still
    mark suppressions used, so the caller flushes it last."""
    if rules is not None:
        # engine-level pseudo-rules (parse-error, unused-suppression) are
        # not in the registry; drop them before the lookup
        from tools.graftlint.rules import RULE_IDS
        selected = get_rules([r for r in rules if r in RULE_IDS])
    else:
        selected = ALL_RULES
    for rule in selected:
        for v in rule.check(ctx):
            (suppressed if ctx.is_suppressed(v) else kept).append(v)


def _flush_unused_suppressions(ctx: FileContext,
                               rules: Optional[Sequence[str]],
                               kept: List[Violation]) -> None:
    # dead allow-comments are debt too: a suppression that matched nothing
    # would silently mask a future regression on that line (the comment
    # ratchet, mirroring the stale-baseline check)
    if rules is not None and "unused-suppression" not in rules:
        return
    for s in ctx.suppressions:
        if not s.used:
            kept.append(Violation(
                rule="unused-suppression", path=ctx.rel_path,
                line=s.line, col=0, severity=SEV_ERROR,
                message=(
                    f"allow[{','.join(sorted(s.rules))}] suppresses "
                    "nothing — the hazard was fixed, so delete the "
                    "comment"),
                symbol="<module>", snippet=ctx.line_snippet(s.line)))


def _concurrency_selected(rules: Optional[Sequence[str]]) -> bool:
    from tools.graftlint.concurrency import CONCURRENCY_RULE_IDS
    return rules is None or bool(set(rules) & set(CONCURRENCY_RULE_IDS))


def _errorflow_selected(rules: Optional[Sequence[str]]) -> bool:
    from tools.graftlint.errorflow import ERRORFLOW_RULE_IDS
    return rules is None or bool(set(rules) & set(ERRORFLOW_RULE_IDS))


def _route_model(model, contexts, rules, kept: List[Violation],
                 suppressed: List[Violation]):
    """Route a whole-program model's findings through the same
    suppression pipeline the per-file rules use."""
    selected = set(rules) if rules is not None else None
    for v in model.violations:
        if selected is not None and v.rule not in selected:
            continue
        ctx = contexts.get(v.path)
        if ctx is not None and ctx.is_suppressed(v):
            suppressed.append(v)
        else:
            kept.append(v)
    return model


def _run_concurrency(contexts, meta, cache_path, rules,
                     kept: List[Violation],
                     suppressed: List[Violation]):
    from tools.graftlint import concurrency as conc
    model = conc.check_contexts(contexts, meta, cache_path)
    return _route_model(model, contexts, rules, kept, suppressed)


def _run_errorflow(contexts, meta, cache_path, rules,
                   kept: List[Violation],
                   suppressed: List[Violation]):
    from tools.graftlint import errorflow as ef
    model = ef.check_contexts(contexts, meta, cache_path)
    return _route_model(model, contexts, rules, kept, suppressed)


def lint_source(source: str, rel_path: str,
                rules: Optional[Sequence[str]] = None) -> LintResult:
    """Lint one source string as if it lived at ``rel_path``. The unit
    tests and the CLI share this path, so fixtures exercise exactly the
    production matching logic. The concurrency pass runs degenerately
    over the single file (cross-module propagation needs
    ``concurrency.analyze_sources``)."""
    try:
        ctx = FileContext(source, rel_path)
    except SyntaxError as e:
        v = Violation(
            rule="parse-error", path=rel_path, line=e.lineno or 1,
            col=e.offset or 0, severity=SEV_ERROR,
            message=f"file does not parse: {e.msg}",
            symbol="<module>", snippet="")
        return LintResult(violations=[v], suppressed=[], parse_errors=[v])

    kept: List[Violation] = []
    suppressed: List[Violation] = []
    _per_file_rules(ctx, rules, kept, suppressed)
    concurrency = None
    if _concurrency_selected(rules):
        concurrency = _run_concurrency(
            {ctx.rel_path: ctx}, None, None, rules, kept, suppressed)
    errorflow = None
    if _errorflow_selected(rules):
        errorflow = _run_errorflow(
            {ctx.rel_path: ctx}, None, None, rules, kept, suppressed)
    _flush_unused_suppressions(ctx, rules, kept)
    kept.sort(key=lambda v: (v.line, v.col, v.rule))
    return LintResult(violations=kept, suppressed=suppressed,
                      concurrency=concurrency, errorflow=errorflow)


def iter_python_files(paths: Sequence[str]) -> Iterable[Path]:
    for raw in paths:
        p = Path(raw)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts or _SKIP_FILE_RE.search(f.name):
                    continue
                yield f


def lint_paths(paths: Sequence[str], root: Optional[Path] = None,
               rules: Optional[Sequence[str]] = None,
               concurrency_cache: bool = True) -> LintResult:
    """Lint every ``*.py`` under ``paths`` (files or directories).

    ``root`` anchors the relative paths used in reports, baselines, and
    the prefix-scoped rules; it defaults to the repo this linter is
    vendored in, so the console script works from any cwd.

    Per-file rules run first; the interprocedural concurrency pass then
    runs once over every parsed file (cached on source mtimes — see
    ``tools/graftlint/concurrency.py``) and its findings flow through
    the same suppression and baseline pipeline.
    """
    import time as _time

    t_start = _time.perf_counter()
    root = (root or repo_root()).resolve()
    all_v: List[Violation] = []
    all_s: List[Violation] = []
    parse_errors: List[Violation] = []
    contexts: Dict[str, FileContext] = {}
    meta: Dict[str, Tuple[int, int]] = {}
    n = 0
    for f in iter_python_files(paths):
        n += 1
        try:
            rel = f.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            source = f.read_text(encoding="utf-8")
            st = f.stat()
        except (OSError, UnicodeDecodeError) as e:
            v = Violation(
                rule="parse-error", path=rel, line=1, col=0,
                severity="error",
                message=f"file unreadable: {e}",
                symbol="<module>", snippet="")
            all_v.append(v)
            parse_errors.append(v)
            continue
        try:
            ctx = FileContext(source, rel)
        except SyntaxError as e:
            v = Violation(
                rule="parse-error", path=rel, line=e.lineno or 1,
                col=e.offset or 0, severity=SEV_ERROR,
                message=f"file does not parse: {e.msg}",
                symbol="<module>", snippet="")
            all_v.append(v)
            parse_errors.append(v)
            continue
        contexts[rel] = ctx
        meta[rel] = (st.st_mtime_ns, st.st_size)
        _per_file_rules(ctx, rules, all_v, all_s)

    concurrency = None
    errorflow = None
    timings: Dict[str, float] = {}
    # the committed caches are only meaningful for the canonical full
    # tree; fixture/tmp-path runs must not overwrite them
    want = (repo_root() / "weaviate_tpu").resolve()
    canonical = {Path(p).resolve() for p in paths} == {want}
    if _concurrency_selected(rules) and contexts:
        from tools.graftlint.concurrency import DEFAULT_CACHE

        cache_path = (DEFAULT_CACHE
                      if concurrency_cache and canonical else None)
        concurrency = _run_concurrency(
            contexts, meta, cache_path, rules, all_v, all_s)
        timings["concurrency_s"] = round(concurrency.wall_s, 3)
    if _errorflow_selected(rules) and contexts:
        from tools.graftlint.errorflow import DEFAULT_CACHE as EF_CACHE

        cache_path = (EF_CACHE
                      if concurrency_cache and canonical else None)
        errorflow = _run_errorflow(
            contexts, meta, cache_path, rules, all_v, all_s)
        timings["errorflow_s"] = round(errorflow.wall_s, 3)
    for ctx in contexts.values():
        _flush_unused_suppressions(ctx, rules, all_v)
    all_v.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    timings["total_s"] = round(_time.perf_counter() - t_start, 3)
    return LintResult(violations=all_v, suppressed=all_s,
                      files_checked=n, parse_errors=parse_errors,
                      concurrency=concurrency, errorflow=errorflow,
                      timings=timings)
