"""Shared mtime cache for the whole-program passes.

Both interprocedural passes (``concurrency.py``, ``errorflow.py``) are
pure functions of the analyzed source set, so their results are cached
identically: a JSON sidecar keyed on the pass version plus every file's
``(mtime_ns, size)`` stamp. One invalidation path means the two passes
can never drift — a source edit that re-runs one re-runs the other, and
a pass-version bump invalidates exactly its own sidecar.

The cache is best-effort: a malformed or unwritable sidecar degrades to
a recompute, never an error (read-only checkouts lint fine, just
uncached).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Optional, Tuple


def cache_key(meta: Dict[str, Tuple[int, int]]) -> dict:
    """Canonical file-set stamp: rel path -> [mtime_ns, size], sorted."""
    return {rel: list(mt) for rel, mt in sorted(meta.items())}


def load(cache_path: Optional[Path], version: int,
         meta: Optional[Dict[str, Tuple[int, int]]]) -> Optional[dict]:
    """The cached payload when warm (same version + identical file
    stamps), else None. Malformed caches read as cold."""
    if cache_path is None or meta is None or not cache_path.exists():
        return None
    try:
        data = json.loads(cache_path.read_text(encoding="utf-8"))
        if (data.get("version") == version
                and data.get("files") == cache_key(meta)):
            return data
    except (ValueError, KeyError, TypeError, OSError):
        pass
    return None


def store(cache_path: Optional[Path], version: int,
          meta: Optional[Dict[str, Tuple[int, int]]],
          payload: dict) -> None:
    """Write the sidecar (version + file stamps + pass payload).
    Silently skipped when uncacheable or unwritable."""
    if cache_path is None or meta is None:
        return
    doc = {"version": version, "files": cache_key(meta)}
    doc.update(payload)
    try:
        cache_path.write_text(json.dumps(doc), encoding="utf-8")
    except OSError:
        pass  # read-only checkout: run uncached
