"""`make trace-demo`: boot a node, fire a mixed burst, print a trace tree.

Boots a single-node DB + REST server on a loopback port, runs a small
mixed search/ingest burst through the real HTTP surface (so the spans
come from the actual ingress → QoS → collection → dispatcher path, not
a synthetic fixture), then fetches `/v1/debug/traces`, picks the newest
search trace, and pretty-prints its assembled tree — the five-minute
"what does a trace look like here" tour of docs/tracing.md.

Tier-1 smoke-tests `run()` against the in-proc server; no external
network is touched (everything binds 127.0.0.1).
"""

from __future__ import annotations

import json
import shutil
import tempfile
import urllib.request


def _fetch(base: str, path: str, body=None):
    req = urllib.request.Request(
        base + path,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="GET" if body is None else "POST",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def render_tree(node: dict, prefix: str = "", last: bool = True,
                root: bool = True) -> list[str]:
    """One span per line, box-drawing glyphs, duration + the attributes
    that explain where the time went."""
    attrs = node.get("attributes", {})
    interesting = {k: v for k, v in attrs.items()
                   if k in ("lane", "queue_wait_ms", "queue_ms",
                            "device_ms", "device_phase", "batch_size",
                            "rows", "peer", "node", "tier", "method",
                            "path", "error")}
    extra = (" " + " ".join(f"{k}={v}" for k, v in interesting.items())
             if interesting else "")
    glyph = "" if root else ("└─ " if last else "├─ ")
    status = "" if node.get("status", "OK") == "OK" else " [ERROR]"
    lines = [f"{prefix}{glyph}{node['name']}  "
             f"{node.get('durationMs', 0):.2f}ms{status}{extra}"]
    kids = node.get("children", [])
    child_prefix = prefix + ("" if root else ("   " if last else "│  "))
    for i, kid in enumerate(kids):
        lines.extend(render_tree(kid, child_prefix, i == len(kids) - 1,
                                 root=False))
    return lines


def run(out=print) -> dict:
    """Boot, burst, fetch, print. Returns the rendered trace (for the
    tier-1 smoke test). Everything is torn down before returning."""
    from weaviate_tpu.api.rest import RestAPI
    from weaviate_tpu.core.db import DB

    tmp = tempfile.mkdtemp(prefix="trace-demo-")
    db = api = None
    try:
        db = DB(tmp)
        api = RestAPI(db)
        srv = api.serve(host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{srv.server_port}"

        out("• creating collection Demo (hnsw, 8d) ...")
        _fetch(base, "/v1/schema", {
            "class": "Demo",
            "vectorIndexType": "hnsw",
            "properties": [{"name": "body", "dataType": ["text"]}],
        })
        out("• ingest burst: 3 batches × 16 objects ...")
        for b in range(3):
            _fetch(base, "/v1/batch/objects", [
                {"class": "Demo",
                 "id": f"00000000-0000-0000-0000-{b * 16 + i:012d}",
                 "properties": {"body": f"doc {b * 16 + i}"},
                 "vector": [((b * 16 + i + j) % 7) / 7.0
                            for j in range(8)]}
                for i in range(16)
            ])
        out("• search burst: 8 nearVector queries ...")
        for i in range(8):
            q = [((i + j) % 5) / 5.0 for j in range(8)]
            _fetch(base, "/v1/graphql", {
                "query": "{ Get { Demo(nearVector: {vector: %s}, "
                         "limit: 3) { _additional { id distance } } } }"
                         % json.dumps(q)})

        traces = _fetch(base, "/v1/debug/traces?limit=50")["traces"]
        search = [t for t in traces if t["root"] == "rest.graphql"]
        assert search, "no search trace recorded"
        tid = search[0]["traceId"]
        tree = _fetch(base, f"/v1/debug/traces?trace={tid}")["tree"]
        out("")
        out(f"trace {tid} ({tree['spanCount']} spans, "
            f"{tree['durationMs']:.2f}ms"
            + (", TRUNCATED" if tree["truncated"] else "") + ")")
        for line in render_tree(tree["tree"]):
            out("  " + line)
        exemplars = _fetch(base,
                           "/v1/debug/traces?exemplars=true")["exemplars"]
        if exemplars:
            out("")
            out("worst-observation exemplars (histogram → trace id):")
            for metric, by_labels in exemplars.items():
                for labels, ex in by_labels.items():
                    out(f"  {metric}{labels}: {ex['value'] * 1000:.2f}ms"
                        f" → trace {ex['trace_id']}")
        return tree
    finally:
        if api is not None:
            api.shutdown()
        if db is not None:
            db.close()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    run()
