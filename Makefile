# Developer/CI entry points. Tier-1 itself is driven by ROADMAP.md's
# pytest line; these targets cover the static-analysis side.

.PHONY: lint lint-sarif lint-dot lint-errorflow-dot lint-fix-baseline \
	test trace-demo chaos bench-device

# Full graftlint: every per-file rule plus BOTH interprocedural
# passes — concurrency (lock-order cycles, blocking-under-lock,
# unlocked collective dispatch) and errorflow (unchecked RPC replies,
# budgets minted in flight, unbounded blocking on ingress paths). Both
# models are cached on source mtimes
# (tools/graftlint/.{concurrency,errorflow}_cache.json, one shared
# invalidation path); per-phase wall time is recorded in
# summary.timings of the JSON so tier-1 budget creep is visible in CI
# artifacts (tests/test_lint_clean.py pins the warm run under 15s).
lint:
	@python -m tools.graftlint weaviate_tpu/ --format json

# SARIF 2.1.0 of the NEW violations — renders as code annotations in CI.
lint-sarif:
	@python -m tools.graftlint weaviate_tpu/ --format sarif

# The whole-program lock-order graph (graphviz); cycle edges are red.
# Recipes are @-silenced so the output pipes cleanly:
#   make lint-dot | dot -Tsvg > lock-order.svg
lint-dot:
	@python -m tools.graftlint weaviate_tpu/ --format dot

# The whole-program reply-taint graph (graphviz): RPC/blob/queue taint
# sources, the functions whose returns launder them, and the
# sanitizers that clear them (docs/lint.md "Error-path contracts"):
#   make lint-errorflow-dot | dot -Tsvg > reply-taint.svg
lint-errorflow-dot:
	@python -m tools.graftlint weaviate_tpu/ --format errorflow-dot

lint-fix-baseline:
	python -m tools.graftlint weaviate_tpu/ --fix-baseline

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		-p no:cacheprovider

# The chaos suite, slow soaks included: replica coordination under
# seeded drop/latency/partition faults, the elastic scale-out
# scenario (3->5 nodes under live ingest+search, donor killed
# mid-migration, crash-resume via the rebalance ledger), and the cold
# tier / cluster backup scenarios (kill mid-offload and mid-backup,
# bucket outages, 3-node backup restored into 5 nodes with zero lost
# acked writes), and the closed-loop autoscaling diurnal ramp (3->6->3
# under seeded faults with a leader killed between decision-journal
# and actuation). Runs under both runtime witnesses (conftest default):
# the session FAILS if any lock-order inversion or any serving-scope
# RPC with no live deadline is observed — zero violations is an
# asserted invariant of the chaos suite, not a hope.
chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_replication.py \
		tests/test_rebalance.py tests/test_coldtier_chaos.py \
		tests/test_autoscale.py \
		-q -p no:cacheprovider

# Boot a node on a loopback port, run a mixed search/ingest burst, and
# pretty-print the assembled trace tree from /v1/debug/traces — the
# quickest way to SEE what docs/tracing.md describes. Smoke-tested in
# tier-1 (tests/test_observability.py::test_trace_demo_smoke).
trace-demo:
	JAX_PLATFORMS=cpu python -m tools.trace_demo

# One journaled sweep over every bench config that carries a pending
# perf-flag verdict (utils/perf_flags.py): each run re-records its
# flag's enabled/evidence from live measurements, so a chip session
# settles ALL device verdicts in one command instead of ad-hoc
# per-config invocations. Configs: device_beam_quantized (hnswquant),
# mesh_device_beam (meshbeam), compile_cache (coldstart),
# device_rerank (rerank), device_hybrid (hybrid), device_filter_planes
# (filtered), device_multi_target (multitarget).
bench-device:
	python bench.py --configs \
		hnswquant,meshbeam,coldstart,rerank,hybrid,filtered,multitarget
